"""Chaos matrix harness — N workloads x M seeded fault cells.

The library behind tests/test_chaos_matrix.py (and `microbench.py --chaos`):
each CELL runs one small workload under one seeded fault plan injected at
the RPC frame seam (chaos.py) and asserts the availability contract:

(a) the workload COMPLETES, or raises/returns the documented *typed*
    failure naming the failed component (never a raw 2-minute
    TimeoutError);
(b) recovery lands within the cell's wall-clock BUDGET;
(c) nothing LEAKS: per-node store objects, channel count, and the
    driver's device-object residents return to their pre-cell baseline
    (the LLM workload additionally asserts its KV-block free list drains
    back to full inside the workload itself).

Fault plans are deterministic: counted rules (``after``/``every``/
``times``) plus the plan's seeded RNG for jitter — same seed over the same
frame stream, same injection sequence (pinned by
test_chaos_plane.test_same_seed_same_injection_sequence); each cell's
actual sequence is returned in the cell result for reproduction.

Workloads (each a few seconds unfaulted):
  tasks      task retry loop (12 remote tasks, max_retries)
  actors     actor call fan-out (2 actors x 8 calls)
  pull       2-replica striped pull onto a third node
  broadcast  cut-through relay broadcast to 3 nodes
  devobj     device-object handoff driver -> worker task
  pipeline   compiled-DAG iterations (shm channels + doorbells)
  llm        one LLM-engine streaming request (streaming generator task)

Faults: drop, delay, dup, reset, partition (a victim node severed via
Cluster.partition_node and healed mid-workload by a timer), and kill —
the CRASH column: a seeded plan pushed into the workload's WORKER
processes makes one SIGKILL itself at the Nth matching frame (the raylets
share the test process and cannot be killed; for the two raylet-plane
workloads with no worker in the data path — pull, broadcast — the cell
SIGKILLs a bystander worker via Cluster.kill_role instead, asserting
crash NON-interference). Kill evidence comes from the flight recorder:
the dying side stamps ``chaos_kill`` into its mmap ring first, and the
cell harvests those events from the node postmortem into the injection
log, since the killed process's in-memory plan.log dies with it.
"""

from __future__ import annotations

import gc
import threading
import time

import numpy as np

FAULTS = ("drop", "delay", "dup", "reset", "partition", "kill")
WORKLOAD_NAMES = ("tasks", "actors", "pull", "broadcast", "devobj", "pipeline", "llm")

# Methods whose frames each workload's hot path rides (drop/reset target
# these so the injection provably lands on the workload, not bystander
# heartbeats). delay/dup cells go wide (method=None) on purpose.
_METHODS = {
    "tasks": ["submit_task", "lease_exec", "push_task", "task_done",
              "tasks_done", "request_worker_lease"],
    "actors": ["actor_call", "submit_task", "task_done", "tasks_done"],
    "pull": ["fetch_object_info", "fetch_object_chunk", "raw_chunk"],
    "broadcast": ["push_begin", "push_chunk", "raw_chunk", "push_commit"],
    "devobj": ["devobj_pull", "p2p_data", "get_inline", "lease_exec",
               "tasks_done"],
    "pipeline": ["channel_doorbell", "channel_data", "actor_call",
                 "channel_create"],
    "llm": ["stream_item", "lease_exec", "tasks_done", "push_task"],
}

# Crash column: per-workload kill rules for the WORKER-side frames the
# workload rides (the plan is pushed into worker processes; a raylet-plane
# frame can never match there). `after` picks the Nth matching frame —
# counted firing, no RNG — so the kill point is deterministic per seed by
# construction. pull/broadcast have no worker in their data path and use
# the kill_role bystander kill instead.
_KILL_RULES = {
    "tasks": {"method": ["task_done", "tasks_done"], "after": 1},
    "actors": {"method": ["actor_call"], "side": "resp", "after": 2},
    "devobj": {"method": ["task_done", "tasks_done"], "after": 0},
    "pipeline": {"method": ["channel_doorbell", "channel_data", "actor_call"],
                 "after": 2},
    "llm": {"method": ["stream_item"], "after": 2},
}

# Typed failure contract (a): a cell may surface a RayTpuError subclass
# that NAMES a component (ActorDiedError names the actor, TaskError the
# task, DeviceObjectLostError the holder, ...). Timeouts are NOT typed —
# a raw TimeoutError (or GetTimeoutError, which merely restates the
# caller's patience) is exactly the 2-minute-silence failure mode the
# matrix exists to ban.
def _is_typed(e: BaseException) -> bool:
    import ray_tpu.exceptions as ex

    return isinstance(e, ex.RayTpuError) and not isinstance(e, TimeoutError)


class CellResult:
    def __init__(self, workload, fault, seed):
        self.workload = workload
        self.fault = fault
        self.seed = seed
        self.ok = False
        self.error: str | None = None
        self.typed = False
        self.elapsed = 0.0
        self.injected = 0
        self.injection_log: list = []
        self.leaks: dict = {}

    def summary(self) -> dict:
        return {
            "cell": f"{self.workload}x{self.fault}",
            "seed": self.seed, "ok": self.ok, "typed": self.typed,
            "error": self.error, "elapsed_s": round(self.elapsed, 2),
            "injected": self.injected, "leaks": self.leaks,
        }


# ---------------------------------------------------------------------------
# fault plans
# ---------------------------------------------------------------------------


def fault_plan(fault: str, workload: str) -> dict | None:
    """The seeded plan spec for one cell. Bounded (``times``) so every cell
    can complete; `partition` returns None — it is driven by
    partition_node + a heal timer instead of frame rules; `kill` returns
    the worker-push plan (or None for the kill_role workloads) — run_cell
    installs it in the WORKER processes, never this one."""
    methods = _METHODS[workload]
    if fault == "kill":
        rule = _KILL_RULES.get(workload)
        if rule is None:
            return None  # pull/broadcast: kill_role bystander crash
        return {"rules": [dict(rule, kind="kill", times=1)]}
    if fault == "drop":
        return {"rules": [{"kind": "drop", "method": methods, "every": 2, "times": 4}]}
    if fault == "delay":
        return {"rules": [{"kind": "delay", "delay_ms": [10, 60], "every": 3, "times": 24}]}
    if fault == "dup":
        return {"rules": [{"kind": "dup", "every": 2, "times": 24}]}
    if fault == "reset":
        return {"rules": [
            # Tear one frame mid-header and one mid-payload.
            {"kind": "reset", "method": methods, "reset_at": 3, "times": 1},
            {"kind": "reset", "method": methods, "reset_at": 40, "after": 4, "times": 1},
        ]}
    if fault == "partition":
        return None
    raise ValueError(fault)


# ---------------------------------------------------------------------------
# workloads
# ---------------------------------------------------------------------------


def _wl_tasks(ctx):
    import ray_tpu

    @ray_tpu.remote(max_retries=4)
    def double(i):
        return i * 2

    refs = [double.remote(i) for i in range(12)]
    out = ray_tpu.get(refs, timeout=ctx["budget_s"])
    assert out == [i * 2 for i in range(12)], out


def _wl_actors(ctx):
    import ray_tpu

    @ray_tpu.remote(max_restarts=1)
    class Counter:
        def __init__(self):
            self.n = 0

        def bump(self, k):
            self.n += k
            return self.n

    actors = [Counter.remote() for _ in range(2)]
    try:
        refs = [a.bump.remote(1) for a in actors for _ in range(8)]
        out = ray_tpu.get(refs, timeout=ctx["budget_s"])
        assert sorted(out) == sorted(list(range(1, 9)) * 2), out
    finally:
        for a in actors:
            ray_tpu.kill(a)


def _oid(tag: str) -> str:
    return tag.encode().hex().ljust(56, "0")[:56]


def _seal_raw(io, node, oid, data):
    offset = io.run(node.store.create(oid, len(data)))
    assert offset is not None
    node.arena.write(offset, data)
    node.store.seal(oid)
    io.run(node.gcs.acall(
        "add_object_location", {"object_id": oid, "node_id": node.node_id}
    ))


def _free_all(nodes, oid):
    for n in nodes:
        try:
            n.store.delete(oid)
        except Exception:
            pass


def _prep_pull(ctx):
    """Pre-fault setup: seal the object on nodes[0] and replicate it onto
    nodes[1], so the faulted phase is a clean 2-replica striped pull (and a
    partition of nodes[1] — a SOURCE — exercises failover, not setup)."""
    io, nodes = ctx["io"], ctx["nodes"]
    data = np.random.default_rng(ctx["seed"]).integers(
        0, 255, 6 * 1024 * 1024, dtype=np.uint8
    ).tobytes()
    oid = _oid(f"chaospull{ctx['seed']}")
    ctx["prep"] = {"oid": oid, "data": data}
    _seal_raw(io, nodes[0], oid, data)
    io.run(nodes[1].pull_manager.pull(oid, timeout=60), timeout=60)


def _wl_pull(ctx):
    """Chunked pull with 2 source replicas onto a third node: chunk faults
    must fail over / retry, never corrupt (bytes compared)."""
    io, nodes = ctx["io"], ctx["nodes"]
    oid, data = ctx["prep"]["oid"], ctx["prep"]["data"]
    budget = ctx["budget_s"] * 0.9
    try:
        io.run(nodes[2].pull_manager.pull(oid, timeout=budget), timeout=budget)
        offset, size = io.run(nodes[2].store.get(oid))
        try:
            got = bytes(nodes[2].arena.read(offset, size))
        finally:
            nodes[2].store.release(oid)
        assert got == data, "pulled bytes corrupt"
    finally:
        _free_all(nodes, oid)


def _wl_broadcast(ctx):
    """Cut-through relay broadcast to every other node; a not-ok outcome
    must NAME the failed nodes (the documented typed failure shape)."""
    io, nodes = ctx["io"], ctx["nodes"]
    data = np.random.default_rng(ctx["seed"] + 1).integers(
        0, 255, 5 * 1024 * 1024, dtype=np.uint8
    ).tobytes()
    oid = _oid(f"chaosbcast{ctx['seed']}")
    try:
        _seal_raw(io, nodes[0], oid, data)
        resp = io.run(
            nodes[0].rpc_broadcast_object({
                "object_id": oid,
                "targets": [
                    {"node_id": n.node_id, "address": list(n.address)}
                    for n in nodes[1:]
                ],
                "timeout": ctx["budget_s"] * 0.8,
            }),
            timeout=ctx["budget_s"] * 0.9,
        )
        if not resp.get("ok"):
            # Documented failure shape: failed subtree NODES are named.
            known = {n.node_id for n in nodes}
            assert resp.get("failed"), resp
            assert set(resp["failed"]) <= known, resp
            return
        for n in nodes[1:]:
            offset, size = io.run(n.store.get(oid))
            try:
                assert bytes(n.arena.read(offset, size)) == data
            finally:
                n.store.release(oid)
    finally:
        _free_all(nodes, oid)


def _wl_devobj(ctx):
    """Device-object handoff: driver holds a jax.Array, a worker task
    resolves it through devobj_pull (inline/host fallback on this CPU
    testbed) — loss must surface as DeviceObjectLostError naming the
    holder, never hang."""
    import jax.numpy as jnp

    import ray_tpu

    @ray_tpu.remote(max_retries=2)
    def consume(arr):
        return float(np.asarray(arr).sum())

    ref = ray_tpu.put(jnp.ones(512, jnp.float32), tensor_transport="collective")
    try:
        out = ray_tpu.get(consume.remote(ref), timeout=ctx["budget_s"])
        assert out == 512.0, out
    finally:
        del ref


def _wl_pipeline(ctx):
    """Compiled-DAG iterations over shm channels: doorbell/side-channel
    faults must be healed by the poll backstop; teardown must reclaim every
    channel even after faults."""
    import ray_tpu
    from ray_tpu.dag import InputNode

    @ray_tpu.remote
    class Stage:
        def work(self, x):
            return x + 1

    stages = [Stage.bind() for _ in range(2)]
    compiled = None
    try:
        with InputNode() as inp:
            d = inp
            for s in stages:
                d = s.work.bind(d)
        compiled = d.experimental_compile()
        for i in range(6):
            assert compiled.execute(i).get(timeout=ctx["budget_s"] / 3) == i + 2
    finally:
        if compiled is not None:
            compiled.teardown()


def _wl_llm(ctx):
    """One LLM-engine streaming request: tokens stream back over the wire
    (streaming-generator stream_item frames) while the engine runs in a
    worker; the KV-block free list must drain back to full."""
    import ray_tpu

    # max_retries exceeds the cluster's warm-worker count: a kill-cell
    # retry can land on ANOTHER armed worker (its own kill rule unfired —
    # only the streaming worker emits stream_item) and die again; the
    # attempt budget must outlast every armed worker once.
    @ray_tpu.remote(num_returns="streaming", max_retries=5)
    def llm_stream(n_tokens):
        import jax
        import jax.numpy as jnp

        from ray_tpu.models.transformer import TransformerConfig, init_params
        from ray_tpu.serve.llm import LLMEngine

        cfg = TransformerConfig(
            vocab_size=64, d_model=32, n_layers=1, n_heads=2, n_kv_heads=2,
            d_ff=48, max_seq_len=48, dtype=jnp.float32, remat=False,
        )
        params = init_params(jax.random.PRNGKey(0), cfg)
        eng = LLMEngine(params, cfg, num_slots=1, block_size=4,
                        max_model_len=32, prefill_chunk=4)
        try:
            req = eng.submit([1, 2, 3, 4], max_new_tokens=n_tokens)
            for tok in req:
                yield int(tok)
            s = eng.stats()
            # KV free-list back to baseline INSIDE the engine process.
            assert s["free_blocks"] + s.get("cached_blocks", 0) == s["num_blocks"], s
        finally:
            eng.shutdown()

    gen = llm_stream.remote(6)
    toks = [ray_tpu.get(r, timeout=ctx["budget_s"]) for r in gen]
    assert len(toks) == 6 and all(isinstance(t, int) for t in toks), toks


WORKLOADS = {
    "tasks": _wl_tasks,
    "actors": _wl_actors,
    "pull": _wl_pull,
    "broadcast": _wl_broadcast,
    "devobj": _wl_devobj,
    "pipeline": _wl_pipeline,
    "llm": _wl_llm,
}

# Pre-fault setup phases (run OUTSIDE the fault window): the faulted phase
# must exercise the workload's recovery path, not its scaffolding.
PREPARES = {"pull": _prep_pull}


# ---------------------------------------------------------------------------
# leak checks
# ---------------------------------------------------------------------------


def leak_baseline(ctx) -> dict:
    from ray_tpu.experimental.device_object.manager import active_manager

    gc.collect()
    mgr = active_manager()
    return {
        "store_objects": [n.store.usage()["num_objects"] for n in ctx["nodes"]],
        "channels": [n.store.usage()["num_channels"] for n in ctx["nodes"]],
        "devobj_resident": 0 if mgr is None else mgr.usage()["resident_count"],
    }


def leak_check(ctx, baseline: dict, settle_s: float = 20.0) -> dict:
    """Wait (frees are async) for every counter to return to baseline;
    returns {} when clean, else the surviving diffs."""
    deadline = time.monotonic() + settle_s
    diff: dict = {}
    while time.monotonic() < deadline:
        gc.collect()
        cur = leak_baseline(ctx)
        diff = {
            k: {"before": baseline[k], "after": cur[k]}
            for k in baseline
            if cur[k] != baseline[k]
        }
        if not diff:
            return {}
        time.sleep(0.25)
    return diff


# ---------------------------------------------------------------------------
# kill-cell plumbing (crash column)
# ---------------------------------------------------------------------------


def _live_worker_clients(ctx):
    out = []
    for n in ctx["nodes"]:
        for w in n.workers.values():
            if w.client is not None and w.state not in ("starting", "dead"):
                out.append(w)
    return out


def _push_plan_to_workers(ctx, plan, seed) -> list:
    """Install a plan in every live WORKER process (the kill victims); the
    driver/raylet process never sees it. Returns the workers reached."""
    io, pushed = ctx["io"], []
    for w in _live_worker_clients(ctx):
        try:
            io.run(
                w.client.acall(
                    "chaos_set_plan", {"plan": plan, "seed": seed},
                    timeout=5, retries=0,
                ),
                timeout=6,
            )
            pushed.append(w)
        except Exception:
            pass  # already-dying workers are, well, chaos
    return pushed


def _collect_kill_events(ctx, since_wall: float) -> list:
    """The killed process's plan.log died with it; its chaos_kill flight
    event survived in the mmap ring. Harvest the node postmortem (raylets
    share one session flight dir) into the cell's injection log."""
    try:
        resp = ctx["io"].run(ctx["nodes"][0].rpc_debug_dump({}), timeout=15)
    except Exception:
        return []
    out = []
    for proc in resp.get("processes", []):
        for ev in proc.get("events", []):
            if ev.get("type") == "chaos_kill" and ev.get("ts", 0) >= since_wall - 2.0:
                out.append(f"kill:{ev.get('detail', '')}")
    return out


# ---------------------------------------------------------------------------
# the cell runner
# ---------------------------------------------------------------------------


def run_cell(ctx, workload: str, fault: str, seed: int,
             budget_s: float = 60.0) -> CellResult:
    """Run one (workload, fault) cell under its seeded plan. Asserts
    nothing itself — returns a CellResult the caller asserts on (the test
    layer and the bench artifact share this)."""
    from ray_tpu._private import chaos
    from ray_tpu._private.chaos import CHAOS_STATS

    res = CellResult(workload, fault, seed)
    ctx = dict(ctx, budget_s=budget_s, seed=seed)
    baseline = leak_baseline(ctx)
    prep = PREPARES.get(workload)
    if prep is not None:
        prep(ctx)  # pre-fault: the cell measures recovery, not setup
    injected_before = CHAOS_STATS.injected
    heal_timer = None
    plan = None
    pushed_kill: list = []
    t_wall0 = time.time()
    t0 = time.monotonic()
    try:
        if fault == "kill":
            spec = fault_plan("kill", workload)
            if spec is None:
                # Raylet-plane workload: SIGKILL a bystander worker process
                # (crash NON-interference — the data path must not notice).
                # Earlier kill cells may have eaten every warm worker, so
                # spawn one to sacrifice if none is live.
                if not ctx["cluster"]._live_workers():
                    import ray_tpu

                    @ray_tpu.remote
                    def _sacrifice():
                        return 1

                    assert ray_tpu.get(_sacrifice.remote(), timeout=60) == 1
                ctx["cluster"].kill_role("worker")
            else:
                # Arm AFTER re-warming the worker pool: a prior cell may
                # have consumed workers (the actors workload kills its
                # actor workers), and a workload task landing in a FRESH
                # worker spawned after the push would run unarmed — the
                # cell would pass with zero injections, which the subset
                # rightly rejects.
                import ray_tpu

                @ray_tpu.remote
                def _warm_pool():
                    return 1

                ray_tpu.get(
                    [_warm_pool.remote() for _ in range(len(ctx["nodes"]))],
                    timeout=60,
                )
                pushed_kill = _push_plan_to_workers(ctx, spec, seed)
        elif fault == "partition":
            # Sever a victim raylet (never nodes[0]: the driver's head node
            # going dark is driver death, a different chaos class), heal
            # mid-workload. The window stays under node_death_timeout_s so
            # the cell exercises transport recovery; the full
            # die-and-rejoin path has its own dedicated test.
            victim = ctx["nodes"][1]
            ctx["cluster"].partition_node(victim)
            heal_timer = threading.Timer(
                ctx.get("partition_s", 1.5),
                lambda: ctx["cluster"].heal_node(victim),
            )
            heal_timer.daemon = True
            heal_timer.start()
        else:
            plan = chaos.install(fault_plan(fault, workload), seed=seed)
        WORKLOADS[workload](ctx)
        res.ok = True
    except Exception as e:  # noqa: BLE001 — the cell judges the class
        res.error = f"{type(e).__name__}: {e}"
        res.typed = _is_typed(e)
    finally:
        if heal_timer is not None:
            heal_timer.cancel()
            ctx["cluster"].heal_node(ctx["nodes"][1])
        if plan is not None:
            res.injection_log = list(plan.log)
        if pushed_kill:
            # Disarm survivors (the fired victim is dead and unreachable).
            for w in pushed_kill:
                try:
                    ctx["io"].run(
                        w.client.acall(
                            "chaos_set_plan", {"plan": None}, timeout=5, retries=0
                        ),
                        timeout=6,
                    )
                except Exception:
                    pass
        chaos.clear()
    res.elapsed = time.monotonic() - t0
    res.injected = CHAOS_STATS.injected - injected_before
    if fault == "kill":
        # Kill evidence lives in the flight postmortem, not this process's
        # counters (the victim's plan died with it; kill_role stamps the
        # driver ring, plan-driven kills stamp the victim's).
        res.injection_log = _collect_kill_events(ctx, t_wall0)
        res.injected = max(res.injected, len(res.injection_log))
    res.leaks = leak_check(ctx, baseline)
    return res


def assert_cell(res: CellResult, budget_s: float):
    """Contract (a)+(b)+(c) for one cell."""
    assert res.ok or res.typed, (
        f"cell {res.workload}x{res.fault} failed UNTYPED: {res.error} "
        f"(injections: {res.injection_log})"
    )
    assert res.elapsed <= budget_s, (
        f"cell {res.workload}x{res.fault} blew its recovery budget: "
        f"{res.elapsed:.1f}s > {budget_s}s"
    )
    assert not res.leaks, (
        f"cell {res.workload}x{res.fault} leaked: {res.leaks}"
    )


# ---------------------------------------------------------------------------
# Sim-scale SLO cells (ISSUE 19): the same seeded-chaos philosophy at
# 100-1000 raylet shells via _private/simnode. A cell builds its own
# SimCluster, drives closed-loop SimTraffic while injecting its fault, and
# returns an SLO scorecard: p99 placement latency, dropped streams, and the
# typed-failure contract (never a raw TimeoutError). Everything is seeded —
# reproduce a scorecard from its seed (see CHAOS.md).
# ---------------------------------------------------------------------------

SIM_CELLS = ("node_kill", "partition_heal_storm", "rolling_update")


class SimCellResult:
    def __init__(self, cell, seed, num_nodes):
        self.cell = cell
        self.seed = seed
        self.num_nodes = num_nodes
        self.ok = False
        self.error: str | None = None
        self.elapsed = 0.0
        self.slo: dict = {}

    def summary(self) -> dict:
        return {
            "cell": self.cell, "seed": self.seed, "nodes": self.num_nodes,
            "ok": self.ok, "error": self.error,
            "elapsed_s": round(self.elapsed, 2), "slo": self.slo,
        }


def _sim_config(heartbeat_s=0.2, death_timeout_s=1.5, **extra) -> dict:
    cfg = {
        "heartbeat_interval_s": heartbeat_s,
        "node_death_timeout_s": death_timeout_s,
        # Fast, deterministic-ish rejoin at test cadence.
        "rejoin_backoff_base_s": 0.02,
        "rejoin_backoff_max_s": 0.5,
    }
    cfg.update(extra)
    return cfg


def _untyped(failures: dict) -> list:
    """Failure-type names that violate the typed contract. SimTraffic
    converts every loss to a RayTpuError subclass; anything resembling a
    bare timeout here is a bug."""
    return [
        name for name in failures
        if "Timeout" in name and name != "GetTimeoutError"
        or name in ("TimeoutError", "CancelledError", "Exception")
    ]


def run_sim_node_kill(num_nodes=96, seed=11, kills=8, duration_s=5.0,
                      p99_budget_ms=2000.0) -> SimCellResult:
    """Seeded node-kill under diurnal traffic: kill `kills` seeded-chosen
    non-entry shells mid-run. SLO: traffic keeps completing, every failure
    typed, post-recovery p99 placement under budget."""
    import random as _random

    from ray_tpu._private.simnode import SimCluster, SimTraffic

    res = SimCellResult("node_kill", seed, num_nodes)
    t0 = time.time()
    c = SimCluster(num_nodes, resources_per_node={"CPU": 4},
                   _system_config=_sim_config(), seed=seed)
    try:
        c.start()
        c.wait_for_view(timeout=60)
        rng = _random.Random(seed)
        victims = rng.sample(
            [n for n in c.nodes if n not in c.entry_nodes], kills
        )
        traffic = SimTraffic(c, users=16, pattern="diurnal", think_s=0.01,
                             sim_ms=5.0, task_timeout_s=3.0, seed=seed)
        killed = []

        def _assassin():
            time.sleep(duration_s * 0.3)
            for v in victims:
                c.kill_node(v)
                killed.append(v.node_id)

        th = threading.Thread(target=_assassin, daemon=True)
        th.start()
        stats = traffic.run(duration_s)
        th.join(timeout=30)
        untyped = _untyped(stats["failures"])
        # Post-kill placements only: the SLO judges recovery, not the
        # pre-fault warmup.
        p99_ms = 0.0
        lat = c.placement_latencies()
        if lat:
            tail = sorted(lat[len(lat) // 2:])
            p99_ms = tail[min(len(tail) - 1, int(0.99 * len(tail)))] * 1000.0
        res.slo = {
            "completed": stats["completed"],
            "submitted": stats["submitted"],
            "failures": stats["failures"],
            "resubmits": stats["resubmits"],
            "killed": len(killed),
            "untyped": untyped,
            "p99_placement_ms": round(p99_ms, 2),
            "p99_budget_ms": p99_budget_ms,
        }
        res.ok = (
            stats["completed"] > 0
            and not untyped
            and len(killed) == kills
            and p99_ms <= p99_budget_ms
        )
        if not res.ok and res.error is None:
            res.error = f"slo violation: {res.slo}"
    except Exception as e:  # noqa: BLE001 — scorecard judges
        res.error = f"{type(e).__name__}: {e}"
    finally:
        c.shutdown()
    res.elapsed = time.time() - t0
    return res


def run_sim_partition_heal_storm(num_nodes=96, seed=23, victims=24,
                                 duration_s=6.0) -> SimCellResult:
    """Partition a quarter of the fleet past the death timeout, then heal
    ALL at once: the rejoin storm the jittered backoff exists to flatten.
    SLO: every victim back ALIVE within budget, node-row count unchanged
    (no duplicate registrations), traffic failures all typed."""
    import random as _random

    from ray_tpu._private.simnode import SimCluster, SimTraffic

    res = SimCellResult("partition_heal_storm", seed, num_nodes)
    t0 = time.time()
    c = SimCluster(num_nodes, resources_per_node={"CPU": 4},
                   _system_config=_sim_config(), seed=seed)
    try:
        c.start()
        c.wait_for_view(timeout=60)
        rows_before = len(c.gcs.nodes)
        rng = _random.Random(seed)
        chosen = rng.sample(
            [n for n in c.nodes if n not in c.entry_nodes], victims
        )
        traffic = SimTraffic(c, users=12, pattern="bursty", think_s=0.01,
                             sim_ms=5.0, task_timeout_s=3.0, seed=seed)

        def _storm():
            time.sleep(duration_s * 0.2)
            for v in chosen:
                c.partition_node(v, True)
            # Hold past the death timeout so the GCS writes them off...
            time.sleep(2.5)
            # ...then heal EVERYONE in the same instant.
            for v in chosen:
                c.partition_node(v, False)

        th = threading.Thread(target=_storm, daemon=True)
        th.start()
        stats = traffic.run(duration_s)
        th.join(timeout=30)
        deadline = time.time() + 20
        back = 0
        while time.time() < deadline:
            back = sum(
                1 for v in chosen
                if c.gcs.nodes.get(v.node_id, {}).get("state") == "ALIVE"
            )
            if back == len(chosen):
                break
            time.sleep(0.1)
        untyped = _untyped(stats["failures"])
        res.slo = {
            "completed": stats["completed"],
            "failures": stats["failures"],
            "untyped": untyped,
            "victims": len(chosen),
            "rejoined": back,
            "node_rows_before": rows_before,
            "node_rows_after": len(c.gcs.nodes),
        }
        res.ok = (
            back == len(chosen)
            and len(c.gcs.nodes) == rows_before  # rejoin != re-register anew
            and not untyped
            and stats["completed"] > 0
        )
        if not res.ok and res.error is None:
            res.error = f"slo violation: {res.slo}"
    except Exception as e:  # noqa: BLE001
        res.error = f"{type(e).__name__}: {e}"
    finally:
        c.shutdown()
    res.elapsed = time.time() - t0
    return res


def run_sim_rolling_update(num_nodes=64, seed=37, streams=12,
                           chunks_per_stream=20,
                           graceful=True) -> SimCellResult:
    """Rolling update: `streams` pinned task streams (node:<id> chunks)
    while every hosting shell is drained (graceful=True) or killed
    (graceful=False) one by one; the driver repins a stream when its host
    leaves. SLO (graceful): ZERO dropped streams — every chunk of every
    stream completes. The abrupt arm is the measured contrast: drops there
    are expected and must be TYPED."""
    import asyncio as _asyncio
    import random as _random

    from ray_tpu._private.simnode import SimCluster
    from ray_tpu.exceptions import NodeDiedError, RayTpuError

    res = SimCellResult(
        "rolling_update" if graceful else "rolling_update_abrupt",
        seed, num_nodes,
    )
    t0 = time.time()
    c = SimCluster(num_nodes, resources_per_node={"CPU": 4},
                   _system_config=_sim_config(), seed=seed)
    try:
        c.start()
        c.wait_for_view(timeout=60)
        rng = _random.Random(seed)
        hosts = rng.sample(
            [n for n in c.nodes if n not in c.entry_nodes], streams
        )
        pins = {i: hosts[i] for i in range(streams)}
        dropped: list = []
        typed_drops: list = []

        async def _stream(i):
            for _chunk in range(chunks_per_stream):
                node = pins[i]
                if node._draining or node._dead:
                    # Host is going away: repin to a live shell (the
                    # rolling-update driver's job).
                    node = rng.choice(c.alive_nodes())
                    pins[i] = node
                spec = c.make_spec(
                    sim_ms=10.0, strategy=f"node:{node.node_id}"
                )
                fut = c.register_waiter(spec.task_id)
                try:
                    await c.asubmit(spec)
                    await _asyncio.wait_for(fut, 3.0)
                except BaseException as e:  # noqa: BLE001 — typed below
                    c.discard_waiter(spec.task_id)
                    err = (
                        e
                        if isinstance(e, RayTpuError)
                        and not isinstance(e, TimeoutError)
                        else NodeDiedError(
                            f"stream {i} chunk lost: {type(e).__name__}"
                        )
                    )
                    dropped.append(i)
                    typed_drops.append(type(err).__name__)
                    return

        async def _run_streams():
            await _asyncio.gather(*[_stream(i) for i in range(streams)])

        def _roller():
            for host in hosts:
                time.sleep(0.25)
                if graceful:
                    c.drain_node(host)
                else:
                    c.kill_node(host)

        th = threading.Thread(target=_roller, daemon=True)
        th.start()
        c._io.run(_run_streams(), timeout=180)
        th.join(timeout=60)
        res.slo = {
            "streams": streams,
            "chunks_per_stream": chunks_per_stream,
            "dropped_streams": len(set(dropped)),
            "drop_types": sorted(set(typed_drops)),
            "graceful": graceful,
        }
        if graceful:
            res.ok = not dropped  # zero dropped streams on graceful drain
        else:
            # Abrupt arm: drops are expected but must be typed.
            res.ok = all(t == "NodeDiedError" for t in typed_drops)
        if not res.ok and res.error is None:
            res.error = f"slo violation: {res.slo}"
    except Exception as e:  # noqa: BLE001
        res.error = f"{type(e).__name__}: {e}"
    finally:
        c.shutdown()
    res.elapsed = time.time() - t0
    return res


def run_sim_matrix(num_nodes=96, seed=7, quick=False) -> list:
    """The sim-scale scorecard: one SimCellResult per cell. Seeded end to
    end — rerun with the same arguments to reproduce a scorecard."""
    n = max(32, num_nodes // 2) if quick else num_nodes
    return [
        run_sim_node_kill(num_nodes=n, seed=seed + 11,
                          kills=max(4, n // 12)),
        run_sim_partition_heal_storm(num_nodes=n, seed=seed + 23,
                                     victims=max(8, n // 4)),
        run_sim_rolling_update(num_nodes=max(32, n // 2), seed=seed + 37,
                               graceful=True),
        run_sim_rolling_update(num_nodes=max(32, n // 2), seed=seed + 37,
                               graceful=False),
    ]
