"""Race detection over the native layer (reference: TSAN/ASAN Bazel configs
.bazelrc:95-115 run in CI over the C++ tests; VERDICT r1 #8).

Two attack angles on shm_index's lock-free reader-pin/tombstone/ABA protocol:
- ThreadSanitizer over an in-process hammer (tests/native/tsan_shm_index.cc):
  formal data races abort the run.
- A multi-PROCESS hammer through the real ctypes binding: concurrent
  put/seal/remove with key reuse in the daemon vs pin/validate/release in
  reader processes, asserting payload integrity (a broken protocol surfaces
  as a torn or misrouted read).
"""

import multiprocessing
import os
import shutil
import subprocess
import sys
import time

import pytest

_HERE = os.path.dirname(os.path.abspath(__file__))




def test_tsan_shm_index_hammer(tmp_path):
    _tsan_build_and_run(tmp_path, "tsan_shm_index.cc", "shm_index.cc", "tsan_idx")


def _reader_proc(name, seconds, err_queue):
    from ray_tpu._private.store import index as idx_mod

    ix = idx_mod.attach_index(name)
    if ix is None:
        err_queue.put("attach failed")
        return
    deadline = time.monotonic() + seconds
    hits = 0
    try:
        while time.monotonic() < deadline:
            for i in range(24):
                oid = f"{i:02x}" * 28  # 28-byte key (56 hex chars)
                got = ix.get_pinned(oid)
                if got is None:
                    continue
                offset, size, token = got
                if size != 1000 + i:
                    err_queue.put(f"bad payload key={i} size={size}")
                    return
                ix.release(token)
                hits += 1
        err_queue.put(f"ok:{hits}")
    finally:
        ix.close()


def test_multiprocess_shm_index_hammer():
    from ray_tpu._private.store import index as idx_mod

    name = f"/rtpu_idx_mp_{os.getpid()}"
    ix = idx_mod.create_index(name, nslots=64)
    if ix is None:
        pytest.skip("native shm_index unavailable (no compiler)")
    ctx = multiprocessing.get_context("spawn")
    errq = ctx.Queue()
    seconds = 3.0
    readers = [ctx.Process(target=_reader_proc, args=(name, seconds, errq)) for _ in range(2)]
    for r in readers:
        r.start()
    deadline = time.monotonic() + seconds + 0.5
    gen = 0
    try:
        while time.monotonic() < deadline:
            for i in range(24):
                oid = f"{i:02x}" * 28
                if ix.put(oid, gen * 4096 + i, 1000 + i):
                    ix.seal(oid)
            for i in range(0, 24, 2):
                oid = f"{i:02x}" * 28
                ix.remove(oid)  # may defer under live pins
            gen += 1
        results = []
        for r in readers:
            r.join(timeout=60)
            assert r.exitcode == 0
        while not errq.empty():
            results.append(errq.get_nowait())
        assert len(results) == 2, results
        for res in results:
            assert res.startswith("ok:"), res
        total = sum(int(r.split(":")[1]) for r in results)
        assert total > 0, "readers never resolved a single object"
    finally:
        for r in readers:
            if r.is_alive():
                r.terminate()
        ix.close(unlink=True)


def test_tsan_builds_all_native_components(tmp_path):
    """All three native components compile under -fsanitize=thread (the
    reference's .bazelrc keeps TSAN configs buildable at all times). shm_arena
    and sched_core are single-writer/event-loop-confined so the shm_index
    hammer above is where the thread pressure goes; this keeps them
    instrumentable for future hammers."""
    gxx = shutil.which("g++")
    if gxx is None:
        pytest.skip("no g++")
    native = os.path.join(os.path.dirname(_HERE), "ray_tpu", "_native")
    for src in ("shm_arena.cc", "sched_core.cc"):
        out = str(tmp_path / (src + ".so"))
        build = subprocess.run(
            [gxx, "-fsanitize=thread", "-O1", "-fPIC", "-shared", "-std=c++17",
             os.path.join(native, src), "-o", out, "-lrt", "-lpthread"],
            capture_output=True,
            text=True,
            timeout=300,
        )
        assert build.returncode == 0, f"{src} TSAN build failed:\n{build.stderr[-2000:]}"


def _tsan_build_and_run(tmp_path, driver_name, src_name, binary_name, seconds="3",
                        include_dirs=()):
    gxx = shutil.which("g++")
    if gxx is None:
        pytest.skip("no g++")
    driver = os.path.join(_HERE, "native", driver_name)
    srcs = [driver]
    if src_name is not None:  # header-only drivers pass src_name=None
        srcs.append(os.path.join(os.path.dirname(_HERE), "ray_tpu", "_native", src_name))
    binary = str(tmp_path / binary_name)
    build = subprocess.run(
        [gxx, "-fsanitize=thread", "-O1", "-g", "-std=c++17", *srcs,
         *[f"-I{d}" for d in include_dirs],
         "-o", binary, "-lrt", "-lpthread"],
        capture_output=True, text=True, timeout=300,
    )
    if build.returncode != 0:
        if "tsan" in (build.stderr or "").lower():
            pytest.skip(f"TSAN runtime unavailable: {build.stderr[-400:]}")
        raise AssertionError(f"TSAN build failed:\n{build.stderr[-3000:]}")
    env = dict(os.environ)
    env["TSAN_OPTIONS"] = "halt_on_error=1 exitcode=66"
    proc = subprocess.run(
        [binary, seconds], capture_output=True, text=True, timeout=300, env=env
    )
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, f"TSAN hammer failed (rc={proc.returncode}):\n{out[-4000:]}"
    assert "HAMMER_OK" in proc.stdout
    assert "ThreadSanitizer" not in out


def test_tsan_shm_arena_hammer(tmp_path):
    """Allocator under concurrency: TSAN over alloc/free/coalesce/stats plus
    the hammer's own overlap/torn-payload/leak oracles (VERDICT r2 weak #7:
    sanitizer coverage was shm_index-only)."""
    _tsan_build_and_run(tmp_path, "tsan_shm_arena.cc", "shm_arena.cc", "tsan_arena")


def test_tsan_sched_core_hammer(tmp_path):
    """Scheduler resource ledger under concurrent acquire/release vs
    heartbeat view resets, node churn, and PG pool prepare/return; asserts
    availability stays within [0, total] throughout."""
    _tsan_build_and_run(tmp_path, "tsan_sched_core.cc", "sched_core.cc", "tsan_sched")


def test_tsan_wire_hammer(tmp_path):
    """The r6 warm-lease wire structs (cpp/ray_tpu_wire.h: send_all/frame/
    read_exact/RpcClient) under concurrent frame write vs. connection reset:
    a torn frame, a SIGPIPE death, a teardown data race, or a hung call()
    against a resetting peer all fail the run (header-only: the driver
    includes cpp/ directly)."""
    _tsan_build_and_run(
        tmp_path, "tsan_wire.cc", None, "tsan_wire",
        include_dirs=(os.path.join(os.path.dirname(_HERE), "cpp"),),
    )
