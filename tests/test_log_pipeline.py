"""Log pipeline tests (reference: python/ray/tests/test_output.py — worker
prints stream back to the driver)."""

import sys
import time

import ray_tpu


def test_worker_prints_reach_driver(capfd):
    ray_tpu.init(num_cpus=2, object_store_memory=64 * 1024 * 1024)
    try:

        @ray_tpu.remote
        def chatty():
            print("hello-from-worker-stdout")
            print("warn-from-worker-stderr", file=sys.stderr)
            return 1

        assert ray_tpu.get(chatty.remote()) == 1
        deadline = time.time() + 15
        out = err = ""
        while time.time() < deadline:
            captured = capfd.readouterr()
            out += captured.out
            err += captured.err
            if "hello-from-worker-stdout" in out and "warn-from-worker-stderr" in err:
                break
            time.sleep(0.3)
        assert "hello-from-worker-stdout" in out
        assert "(chatty pid=" in out  # reference-style prefix
        assert "warn-from-worker-stderr" in err
    finally:
        ray_tpu.shutdown()


def test_log_to_driver_disabled(capfd, monkeypatch):
    monkeypatch.setenv("RAY_TPU_LOG_TO_DRIVER", "0")
    ray_tpu.init(num_cpus=2, object_store_memory=64 * 1024 * 1024)
    try:

        @ray_tpu.remote
        def quiet():
            print("should-not-appear")
            return 1

        assert ray_tpu.get(quiet.remote()) == 1
        time.sleep(2.0)
        captured = capfd.readouterr()
        assert "should-not-appear" not in captured.out
    finally:
        ray_tpu.shutdown()
