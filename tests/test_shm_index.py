"""Native shm object-index tests.

Modeled on the reference's plasma client/store tests
(src/ray/object_manager/plasma/test/): put/seal/lookup/pin/remove protocol,
deferred frees under pins, and the client fast path end-to-end.
"""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private.store.index import attach_index, create_index


@pytest.fixture
def index():
    name = f"/rtpu_test_idx_{os.getpid()}"
    ix = create_index(name, nslots=64)
    if ix is None:
        pytest.skip("native index unavailable")
    yield ix
    ix.close(unlink=True)


KEY1 = "aa" * 28
KEY2 = "bb" * 28


def test_put_seal_lookup(index):
    assert index.put(KEY1, 128, 4096)
    # Unsealed: clients must miss.
    assert index.get_pinned(KEY1) is None
    assert index.seal(KEY1)
    hit = index.get_pinned(KEY1)
    assert hit is not None
    offset, size, token = hit
    assert (offset, size) == (128, 4096)
    index.release(token)


def test_attacher_sees_owner_writes(index):
    other = attach_index(index.name)
    assert other is not None
    index.put(KEY1, 64, 100)
    index.seal(KEY1)
    hit = other.get_pinned(KEY1)
    assert hit is not None and hit[0] == 64
    other.release(hit[2])
    other.close()


def test_remove_defers_under_pin(index):
    index.put(KEY1, 0, 10)
    index.seal(KEY1)
    hit = index.get_pinned(KEY1)
    assert hit is not None
    # Pinned: remove reports busy (1), readers visible.
    assert index.remove(KEY1) == 1
    assert index.readers(KEY1) == 1
    # Tombstoned: new lookups miss.
    assert index.get_pinned(KEY1) is None
    index.release(hit[2])
    assert index.readers(KEY1) == 0


def test_slot_reuse_bumps_version(index):
    index.put(KEY1, 0, 10)
    index.seal(KEY1)
    h1 = index.get_pinned(KEY1)
    index.release(h1[2])
    assert index.remove(KEY1) == 0
    # Same key re-created (reconstruction): version must differ.
    index.put(KEY1, 640, 20)
    index.seal(KEY1)
    h2 = index.get_pinned(KEY1)
    assert h2 is not None
    assert h2[2] != h1[2]  # (slot, version) token differs on re-create
    assert h2[0] == 640
    index.release(h2[2])


def test_many_keys_no_collision_loss(index):
    keys = [("%02x" % i) * 28 for i in range(40)]  # 40 keys in 64 slots
    for i, k in enumerate(keys):
        assert index.put(k, i * 64, 64)
        assert index.seal(k)
    for i, k in enumerate(keys):
        hit = index.get_pinned(k)
        assert hit is not None and hit[0] == i * 64, k
        index.release(hit[2])


def test_local_get_uses_index_fast_path():
    """End-to-end: a large object put through the framework is readable in
    the driver via the index (no RPC), and the data is correct."""
    ray_tpu.init(num_cpus=2, object_store_memory=64 * 1024 * 1024)
    try:
        from ray_tpu._private import worker_context

        cw = worker_context.get_core_worker()
        store = cw.store
        if store.index is None:
            pytest.skip("native index unavailable")
        arr = np.random.default_rng(0).standard_normal(200_000)
        ref = ray_tpu.put(arr)  # > inline threshold -> plasma
        # The index must resolve the object locally.
        hit = store.index.get_pinned(ref.hex())
        assert hit is not None
        store.index.release(hit[2])
        out = ray_tpu.get(ref)
        assert np.array_equal(out, arr)
    finally:
        ray_tpu.shutdown()
