"""Tests: CNN RLModules / ModelCatalog, MultiAgentEnv shared-policy path,
PolicyServer/Client external sims, rllib CLI.

Reference analogs: rllib/models/tests/test_models.py (vision nets),
rllib/env/tests/test_multi_agent_env.py, rllib/tests/test_external_env.py,
rllib/tests/test_rllib_train_and_evaluate.py.
"""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def ray_cluster():
    ray_tpu.init(num_cpus=6, object_store_memory=256 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


# ---------- CNN modules ----------

def test_cnn_module_forward_shapes():
    import gymnasium as gym
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from ray_tpu.rllib.core import rl_module
    from ray_tpu.rllib.core.rl_module import RLModuleSpec

    obs_space = gym.spaces.Box(0, 1, (32, 32, 3), np.float32)
    act_space = gym.spaces.Discrete(4)
    spec = RLModuleSpec.from_spaces(obs_space, act_space, hiddens=(32,))
    assert spec.conv_filters, "3D obs should get a conv torso"
    params = rl_module.init_params(jax.random.PRNGKey(0), spec)
    assert "pi_conv" in params and "vf_conv" in params
    obs = jnp.zeros((5, 32, 32, 3))
    logits, value = rl_module.forward(params, obs, spec)
    assert logits.shape == (5, 4) and value.shape == (5,)
    # Flat input (as rollout batches carry it) reshapes internally.
    logits2, _ = rl_module.forward(params, obs.reshape(5, -1), spec)
    assert np.allclose(np.asarray(logits), np.asarray(logits2))


def test_model_catalog_picks_torso():
    import gymnasium as gym

    from ray_tpu.rllib.models import ModelCatalog

    flat = ModelCatalog.get_model_spec(
        gym.spaces.Box(-1, 1, (8,), np.float32), gym.spaces.Discrete(2)
    )
    assert not flat.conv_filters
    img = ModelCatalog.get_model_spec(
        gym.spaces.Box(0, 255, (84, 84, 4), np.uint8), gym.spaces.Discrete(6),
        {"conv_filters": None, "fcnet_hiddens": (256,)},
    )
    assert img.conv_filters == ((16, 8, 4), (32, 4, 2), (64, 3, 1))
    custom = ModelCatalog.get_model_spec(
        gym.spaces.Box(0, 1, (10, 10, 1), np.float32), gym.spaces.Discrete(2),
        {"conv_filters": [(8, 3, 1)]},
    )
    assert custom.conv_filters == ((8, 3, 1),)
    # Tiny spatial dims fall back to the flat MLP — no collapsing conv stack.
    tiny = ModelCatalog.get_model_spec(
        gym.spaces.Box(0, 1, (2, 2, 1), np.float32), gym.spaces.Discrete(2)
    )
    assert not tiny.conv_filters
    small = ModelCatalog.get_model_spec(
        gym.spaces.Box(0, 1, (4, 4, 1), np.float32), gym.spaces.Discrete(2)
    )
    assert small.conv_filters == ((16, 3, 1),)


def test_ppo_learns_tiny_vision_env(ray_cluster):
    """A trivially-learnable image env: the signal is which half of the image
    is bright; PPO with the conv torso must exceed random reward."""
    import gymnasium as gym
    import jax

    jax.config.update("jax_platforms", "cpu")

    class SideEnv(gym.Env):
        observation_space = gym.spaces.Box(0, 1, (10, 10, 1), np.float32)
        action_space = gym.spaces.Discrete(2)

        def __init__(self, config=None):
            self._rng = np.random.default_rng(0)
            self._t = 0

        def _obs(self):
            img = np.zeros((10, 10, 1), np.float32)
            self.side = int(self._rng.integers(0, 2))
            if self.side == 0:
                img[:, :5] = 1.0
            else:
                img[:, 5:] = 1.0
            return img

        def reset(self, *, seed=None, options=None):
            self._t = 0
            return self._obs(), {}

        def step(self, action):
            r = 1.0 if int(action) == self.side else 0.0
            self._t += 1
            return self._obs(), r, self._t >= 20, False, {}

    from ray_tpu.rllib import PPOConfig

    cfg = (
        PPOConfig()
        .environment(lambda config: SideEnv(config))
        .rollouts(num_rollout_workers=2, num_envs_per_worker=2)
        .training(lr=1e-3, train_batch_size=800, sgd_minibatch_size=128,
                  num_sgd_iter=6, model_hiddens=(32,),
                  model_conv_filters=[(8, 3, 2), (16, 3, 2)])
        .debugging(seed=0)
    )
    algo = cfg.build()
    algo.setup(cfg.to_dict())
    best = 0.0
    try:
        for _ in range(15):
            r = algo.step()
            best = max(best, r["episode_reward_mean"])
            if best >= 16:
                break
        # Random play scores ~10/20; a working conv torso approaches 20.
        assert best >= 16, f"vision PPO failed to learn (best={best})"
    finally:
        algo.cleanup()


# ---------- multi-agent ----------

def test_make_multi_agent_api():
    from ray_tpu.rllib.env import make_multi_agent

    cls = make_multi_agent("CartPole-v1", num_agents=3)
    env = cls({})
    obs, _ = env.reset(seed=0)
    assert set(obs) == {"agent_0", "agent_1", "agent_2"}
    actions = {a: env.action_space.sample() for a in env.possible_agents}
    obs, rewards, terms, truncs, _ = env.step(actions)
    assert set(rewards) == set(actions)
    assert terms["__all__"] is False
    env.close()


def test_multi_agent_vector_env_slots():
    from ray_tpu.rllib.env import make_multi_agent, make_vector_env

    cls = make_multi_agent("CartPole-v1", num_agents=2)
    venv = make_vector_env(lambda config: cls(config), 2, {}, 0, seed=0)
    assert venv.num_envs == 4  # 2 envs x 2 agents
    obs = venv.current_obs()
    assert obs.shape == (4, 4)
    for _ in range(30):
        _, rewards, dones, infos = venv.step(np.zeros(4, np.int64))
    # Always-push CartPole ends episodes; per-slot boundaries recorded.
    r, lens = venv.pop_episode_stats()
    assert len(r) > 0
    venv.close()


def test_ppo_learns_multi_agent_cartpole(ray_cluster):
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib import PPOConfig
    from ray_tpu.rllib.env import make_multi_agent

    ma_cls = make_multi_agent("CartPole-v1", num_agents=2)
    cfg = (
        PPOConfig()
        .environment(lambda config: ma_cls(config))
        .rollouts(num_rollout_workers=2, num_envs_per_worker=2)
        .training(lr=3e-4, train_batch_size=2048, sgd_minibatch_size=256,
                  num_sgd_iter=8, entropy_coeff=0.01)
        .debugging(seed=0)
    )
    algo = cfg.build()
    algo.setup(cfg.to_dict())
    best = 0.0
    try:
        for _ in range(20):
            r = algo.step()
            best = max(best, r["episode_reward_mean"])
            if best >= 120:
                break
        assert best >= 120, f"shared-policy multi-agent PPO failed (best={best})"
    finally:
        algo.cleanup()


# ---------- external env / policy server ----------

def test_policy_server_roundtrip():
    from ray_tpu.rllib.env import PolicyClient, PolicyServerInput

    def compute_action(obs, explore):
        return int(obs.sum() > 0)

    server = PolicyServerInput(compute_action)
    try:
        client = PolicyClient(server.address)
        eid = client.start_episode()
        for t in range(5):
            obs = np.ones(4) * (1 if t % 2 == 0 else -1)
            a = client.get_action(eid, obs)
            assert a == (1 if t % 2 == 0 else 0)
            client.log_returns(eid, 0.5)
        rows = client.end_episode(eid)
        assert rows == 5
        batch = server.next_batch()
        assert batch.count == 5
        assert batch["rewards"].sum() == pytest.approx(2.5)
        assert batch["dones"][-1] == 1.0
        # Several shaping rewards per step accumulate onto that step.
        eid = client.start_episode()
        client.get_action(eid, np.ones(4))
        client.log_returns(eid, 1.0)
        client.log_returns(eid, 0.25)
        assert client.end_episode(eid) == 1
        b2 = server.next_batch()
        assert b2["rewards"][0] == pytest.approx(1.25)
        # Unknown episode -> server-side error surfaced client-side.
        with pytest.raises(Exception):
            client.get_action("nope", np.zeros(4))
    finally:
        server.shutdown()


def test_rllib_cli_train(ray_cluster, capsys):
    from ray_tpu.rllib.train import main

    rc = main([
        "train", "--run", "PPO", "--env", "CartPole-v1",
        "--stop-iters", "2",
        "--config", '{"num_rollout_workers": 1, "train_batch_size": 400, "num_envs_per_worker": 2}',
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "iter 1" in out and "reward=" in out
