"""Serve × models/generate: a jit-compiled LLM decode path behind HTTP.

The end-to-end shape of TPU model serving: replica holds params + compiled
generate(); requests ride the proxy; batched handle calls share one compile.
"""

import json
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(scope="module")
def serve_instance():
    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    serve.start()
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def test_llm_deployment_generates(serve_instance):
    @serve.deployment
    class TinyLM:
        def __init__(self):
            import jax
            import jax.numpy as jnp

            from ray_tpu.models.transformer import TransformerConfig, init_params

            self.cfg = TransformerConfig(
                vocab_size=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=4,
                d_ff=64, max_seq_len=32, dtype=jnp.float32, remat=False,
            )
            self.params = init_params(jax.random.PRNGKey(0), self.cfg)

        def __call__(self, request):
            import jax.numpy as jnp
            import numpy as np

            from ray_tpu.models.generate import generate

            toks = request.json()["tokens"]
            out = generate(
                self.params, jnp.asarray([toks], jnp.int32), self.cfg,
                max_new_tokens=4, temperature=0.0,
            )
            return {"tokens": np.asarray(out)[0].tolist()}

    serve.run(TinyLM.bind(), route_prefix="/llm")
    host, port = serve.http_address()
    req = urllib.request.Request(
        f"http://{host}:{port}/llm",
        data=json.dumps({"tokens": [1, 2, 3]}).encode(),
    )
    out = json.loads(urllib.request.urlopen(req, timeout=60).read())
    assert len(out["tokens"]) == 4
    assert all(0 <= t < 64 for t in out["tokens"])
    # Greedy decode is deterministic: same prompt, same continuation.
    out2 = json.loads(urllib.request.urlopen(req, timeout=60).read())
    assert out2 == out
    serve.delete("TinyLM")


def test_llm_deployment_speculative_sampling(serve_instance):
    """Sampling-mode speculative decoding behind Serve: the deployment holds
    target + draft params and serves temperature/top-p spec-decode; seeded
    requests are reproducible, different seeds vary."""

    @serve.deployment
    class SpecLM:
        def __init__(self):
            import jax
            import jax.numpy as jnp

            from ray_tpu.models.transformer import TransformerConfig, init_params

            mk = lambda d_model, d_ff, layers: TransformerConfig(
                vocab_size=64, d_model=d_model, n_layers=layers, n_heads=4,
                n_kv_heads=4, d_ff=d_ff, max_seq_len=48, dtype=jnp.float32,
                remat=False,
            )
            self.cfg, self.draft_cfg = mk(32, 64, 2), mk(16, 32, 1)
            self.params = init_params(jax.random.PRNGKey(0), self.cfg)
            self.draft_params = init_params(jax.random.PRNGKey(9), self.draft_cfg)

        def __call__(self, request):
            import jax
            import jax.numpy as jnp
            import numpy as np

            from ray_tpu.models.generate import speculative_generate

            body = request.json()
            out, rounds = speculative_generate(
                self.params, self.draft_params,
                jnp.asarray([body["tokens"]], jnp.int32),
                self.cfg, self.draft_cfg, max_new_tokens=6, k=2,
                temperature=0.8, top_p=0.95,
                key=jax.random.PRNGKey(int(body.get("seed", 0))),
            )
            return {"tokens": np.asarray(out)[0].tolist(), "rounds": int(rounds)}

    serve.run(SpecLM.bind(), route_prefix="/speclm")
    host, port = serve.http_address()

    def ask(seed):
        req = urllib.request.Request(
            f"http://{host}:{port}/speclm",
            data=json.dumps({"tokens": [1, 2, 3], "seed": seed}).encode(),
        )
        return json.loads(urllib.request.urlopen(req, timeout=120).read())

    a, b, c = ask(7), ask(7), ask(8)
    assert a == b, "seeded sampling must be reproducible"
    assert len(a["tokens"]) == 6 and all(0 <= t < 64 for t in a["tokens"])
    assert 1 <= a["rounds"] <= 6
    assert c["tokens"] != a["tokens"] or c["rounds"] != a["rounds"]
    serve.delete("SpecLM")
