"""Serve × models/generate: a jit-compiled LLM decode path behind HTTP.

The end-to-end shape of TPU model serving: replica holds params + compiled
generate(); requests ride the proxy; batched handle calls share one compile.
"""

import json
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(scope="module")
def serve_instance():
    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    serve.start()
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def test_llm_deployment_generates(serve_instance):
    @serve.deployment
    class TinyLM:
        def __init__(self):
            import jax
            import jax.numpy as jnp

            from ray_tpu.models.transformer import TransformerConfig, init_params

            self.cfg = TransformerConfig(
                vocab_size=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=4,
                d_ff=64, max_seq_len=32, dtype=jnp.float32, remat=False,
            )
            self.params = init_params(jax.random.PRNGKey(0), self.cfg)

        def __call__(self, request):
            import jax.numpy as jnp
            import numpy as np

            from ray_tpu.models.generate import generate

            toks = request.json()["tokens"]
            out = generate(
                self.params, jnp.asarray([toks], jnp.int32), self.cfg,
                max_new_tokens=4, temperature=0.0,
            )
            return {"tokens": np.asarray(out)[0].tolist()}

    serve.run(TinyLM.bind(), route_prefix="/llm")
    host, port = serve.http_address()
    req = urllib.request.Request(
        f"http://{host}:{port}/llm",
        data=json.dumps({"tokens": [1, 2, 3]}).encode(),
    )
    out = json.loads(urllib.request.urlopen(req, timeout=60).read())
    assert len(out["tokens"]) == 4
    assert all(0 <= t < 64 for t in out["tokens"])
    # Greedy decode is deterministic: same prompt, same continuation.
    out2 = json.loads(urllib.request.urlopen(req, timeout=60).read())
    assert out2 == out
    serve.delete("TinyLM")
