"""Native scheduler core tests (analog of the reference's C++ scheduler unit
tests: cluster_resource_scheduler_test.cc, fixed_point semantics,
hybrid/spread policy tests) — plus a native-vs-Python differential fuzz."""

import numpy as np
import pytest

from ray_tpu._private.sched_core import (
    HYBRID,
    SPREAD,
    _PySchedCore,
    create_sched_core,
)


@pytest.fixture(params=["native", "python"])
def core(request):
    if request.param == "native":
        c = create_sched_core()
        if not c.is_native:
            pytest.skip("native sched core unavailable")
    else:
        c = _PySchedCore()
    yield c
    c.close()


def test_acquire_release_exact_fixed_point(core):
    core.node_upsert("n1", {"CPU": 4, "TPU": 1}, {"CPU": 4, "TPU": 1})
    # 0.1 is inexact in binary floats: 40 x 0.1-CPU acquires must empty the
    # node EXACTLY (the reference uses FixedPoint for the same reason).
    for _ in range(40):
        assert core.try_acquire("n1", {"CPU": 0.1})
    assert core.node_avail("n1", "CPU") == 0.0
    assert not core.try_acquire("n1", {"CPU": 0.1})
    for _ in range(40):
        core.release("n1", {"CPU": 0.1})
    assert core.node_avail("n1", "CPU") == 4.0
    # Release never inflates past the total.
    core.release("n1", {"CPU": 5})
    assert core.node_avail("n1", "CPU") == 4.0


def test_pool_lifecycle(core):
    core.pool_upsert("pg1:0", {"CPU": 2, "TPU": 4})
    assert core.pool_exists("pg1:0")
    assert core.pool_try_acquire("pg1:0", {"TPU": 4})
    assert not core.pool_try_acquire("pg1:0", {"TPU": 1})
    core.pool_release("pg1:0", {"TPU": 4})
    assert core.pool_avail("pg1:0", "TPU") == 4.0
    core.pool_remove("pg1:0")
    assert not core.pool_exists("pg1:0")
    assert not core.pool_try_acquire("pg1:0", {"CPU": 1})


def test_cluster_feasibility_levels(core):
    core.node_upsert("a", {"CPU": 8}, {"CPU": 0})
    assert core.cluster_feasibility({"CPU": 4}) == 1  # feasible, not now
    core.node_upsert("b", {"CPU": 8}, {"CPU": 8})
    assert core.cluster_feasibility({"CPU": 4}) == 2  # fits now
    assert core.cluster_feasibility({"CPU": 100}) == 0  # nowhere
    assert core.cluster_feasibility({"GPU": 1}) == 0  # unknown resource


def test_hybrid_prefers_local_then_spills(core):
    core.node_upsert("local", {"CPU": 4}, {"CPU": 4})
    core.node_upsert("peer", {"CPU": 16}, {"CPU": 16})
    # Local fits now -> stay local (pack).
    assert core.best_node({"CPU": 2}, HYBRID, "local") == "local"
    # Local full but feasible; a peer fits now -> spill to the peer.
    assert core.try_acquire("local", {"CPU": 4})
    assert core.best_node({"CPU": 2}, HYBRID, "local") == "peer"
    # Only feasible-by-total anywhere: local is preferred when feasible.
    assert core.best_node({"CPU": 3}, HYBRID, "local") == "peer"  # peer fits now
    assert core.try_acquire("peer", {"CPU": 16})
    assert core.best_node({"CPU": 3}, HYBRID, "local") == "local"  # queue locally
    # Infeasible locally, feasible on the (full) peer -> peer.
    assert core.best_node({"CPU": 10}, HYBRID, "local") == "peer"
    assert core.best_node({"CPU": 64}, HYBRID, "local") is None


def test_spread_picks_emptiest(core):
    core.node_upsert("a", {"CPU": 8}, {"CPU": 2})
    core.node_upsert("b", {"CPU": 8}, {"CPU": 7})
    core.node_upsert("c", {"CPU": 2}, {"CPU": 2})
    assert core.best_node({"CPU": 1}, SPREAD, "a") in ("b", "c")
    # Feasibility still filters: a 4-CPU shape can't go to the 2-CPU node.
    assert core.best_node({"CPU": 4}, SPREAD, "a") == "b"


def test_native_python_differential_fuzz():
    native = create_sched_core()
    if not native.is_native:
        pytest.skip("native sched core unavailable")
    py = _PySchedCore()
    rng = np.random.default_rng(0)
    names = ["CPU", "TPU", "mem", "custom_x"]
    nodes = [f"n{i}" for i in range(4)]
    for c in (native, py):
        for n in nodes:
            c.node_upsert(n, {"CPU": 8, "TPU": 4, "mem": 100}, {"CPU": 8, "TPU": 4, "mem": 100})
        c.pool_upsert("pg:0", {"CPU": 3, "custom_x": 1.5})
    try:
        for step in range(3000):
            op = rng.integers(0, 5)
            node = nodes[rng.integers(0, len(nodes))]
            demand = {
                names[j]: float(rng.integers(1, 30)) / 10
                for j in rng.choice(len(names), rng.integers(1, 3), replace=False)
            }
            if op == 0:
                assert native.try_acquire(node, demand) == py.try_acquire(node, demand), (step, demand)
            elif op == 1:
                native.release(node, demand)
                py.release(node, demand)
            elif op == 2:
                assert native.pool_try_acquire("pg:0", demand) == py.pool_try_acquire("pg:0", demand)
            elif op == 3:
                native.pool_release("pg:0", demand)
                py.pool_release("pg:0", demand)
            else:
                assert native.cluster_feasibility(demand) == py.cluster_feasibility(demand)
                for strat in (HYBRID, SPREAD):
                    b_n = native.best_node(demand, strat, "n0")
                    b_p = py.best_node(demand, strat, "n0")
                    # Tie-breaking order may differ; both must agree on
                    # feasibility and on the fits-now property of the pick.
                    assert (b_n is None) == (b_p is None), (step, demand, strat, b_n, b_p)
            for n in nodes:
                for name in names:
                    assert native.node_avail(n, name) == pytest.approx(py.node_avail(n, name)), (step, n, name)
    finally:
        native.close()
