"""Controller-level drain/retire semantics (ISSUE 14 satellites), white-box:
the ServeController object is driven directly in the driver process (its
replicas are real actors on a real cluster, but no proxy/HTTP plane), so the
raced-stop no-op branch and the retire-vs-drain ordering are pinned without
a full serve instance. Plus the bounded serve.shutdown() satellite.
"""

import time

import pytest

import ray_tpu
from ray_tpu.serve._private.common import (
    DeploymentConfig,
    DeploymentInfo,
    ReplicaInfo,
)


@pytest.fixture
def drain_cluster(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=4, object_store_memory=64 * 1024 * 1024)
    cluster.connect()
    cluster.wait_for_nodes()
    yield cluster


class SlowCallable:
    """Deployment body whose requests take long enough to straddle a drain."""

    def __call__(self, x, delay=0.0):
        if delay:
            time.sleep(delay)
        return x


def _make_controller(name="draindep", drain_timeout_s=30.0, num_replicas=1):
    import cloudpickle

    from ray_tpu.serve._private.controller import ServeController

    controller = ServeController()
    info = DeploymentInfo(
        name=name,
        app_name="t",
        import_spec=cloudpickle.dumps((SlowCallable, (), {})),
        config=DeploymentConfig(
            num_replicas=num_replicas,
            version="v1",
            drain_timeout_s=drain_timeout_s,
            health_check_period_s=0.5,
            health_check_timeout_s=5.0,
        ),
    )
    controller.deploy([info])
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        if len(controller._replicas.get(name, [])) >= num_replicas:
            return controller, name
        time.sleep(0.1)
    controller.graceful_shutdown()
    raise TimeoutError("replicas never came up")


def test_retire_raced_deliberate_stop_is_noop(drain_cluster):
    """Satellite: _retire_unhealthy_replica on a replica that is NOT in the
    routing table and NOT draining (a raced deliberate stop already took
    it) must be a pure no-op — no epoch bump, no kill, no state change."""
    controller, name = _make_controller(drain_timeout_s=0.0)
    try:
        rinfo = controller._replicas[name][0]
        ghost = ReplicaInfo(
            replica_id="gone1234",
            deployment_name=name,
            actor_name="SERVE_REPLICA::ghost",
            max_concurrent_queries=10,
            version="v1",
        )
        epoch_before = controller._epoch
        controller._retire_unhealthy_replica(name, ghost)
        assert controller._epoch == epoch_before
        assert controller._replicas[name] == [rinfo]
        # The live replica still answers.
        handle = controller._replica_handles[rinfo.replica_id]
        assert ray_tpu.get(
            handle.handle_request.remote("__call__", (7,), {}), timeout=60
        ) == 7
    finally:
        controller.graceful_shutdown()


def test_health_failure_mid_drain_retires_immediately(drain_cluster):
    """Satellite: retire-vs-drain ordering. A deliberate stop starts a
    drain (busy replica -> the drainer waits); a health-check failure DURING
    the drain must claim the drain record and kill NOW — the drainer thread
    yields instead of racing a second kill."""
    controller, name = _make_controller(drain_timeout_s=60.0)
    try:
        rinfo = controller._replicas[name][0]
        handle = controller._replica_handles[rinfo.replica_id]
        # Occupy the replica so the drain cannot complete on its own.
        busy_ref = handle.handle_request.remote("__call__", (1,), {"delay": 20.0})
        time.sleep(0.3)
        controller._stop_replica(name, rinfo)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if rinfo.replica_id in controller._draining:
                break
            time.sleep(0.05)
        assert rinfo.replica_id in controller._draining, "drain never started"
        # The drainer thread's drain() RPC lands asynchronously; wait for
        # the replica to observe it (still busy with the slow request).
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            st = ray_tpu.get(handle.drain_status.remote(), timeout=30)
            if st["draining"]:
                break
            time.sleep(0.05)
        assert st["draining"] is True and st["ongoing"] == 1, st
        # Health failure outranks the drain: immediate retire.
        controller._retire_unhealthy_replica(name, rinfo)
        assert rinfo.replica_id not in controller._draining
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            try:
                ray_tpu.get(handle.drain_status.remote(), timeout=2)
            except Exception:
                break  # actor is gone — the kill landed
            time.sleep(0.2)
        else:
            pytest.fail("replica survived a health-failure retire mid-drain")
        with pytest.raises(Exception):
            ray_tpu.get(busy_ref, timeout=30)
    finally:
        controller.graceful_shutdown()


def test_idle_replica_drains_clean_and_retires(drain_cluster):
    """A deliberate stop of an idle replica drains 'clean' within one poll
    and the process is retired; the drain record does not leak."""
    controller, name = _make_controller(drain_timeout_s=30.0)
    try:
        rinfo = controller._replicas[name][0]
        handle = controller._replica_handles[rinfo.replica_id]
        controller._stop_replica(name, rinfo)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if rinfo.replica_id not in controller._draining:
                try:
                    ray_tpu.get(handle.drain_status.remote(), timeout=2)
                except Exception:
                    break  # retired
            time.sleep(0.2)
        else:
            pytest.fail("idle replica did not retire after clean drain")
        assert rinfo.replica_id not in controller._draining
        assert rinfo not in controller._replicas.get(name, [])
    finally:
        controller.graceful_shutdown()


def test_drain_status_excludes_abandoned_pumps():
    """Clusterless pin: while DRAINING, a stream pump nobody polled for
    _DRAIN_IDLE_EXCLUDE_S is an orphan (its proxy died without
    cancel_stream) and must not hold the drain open for the whole
    drain_timeout_s — the normal 300s idle reaper only runs from
    handle_http_request, which the drain gate refuses. The orphan is
    EXCLUDED from the count, not cancelled: a slow-but-alive consumer must
    never be silently truncated — at retire its next poll gets the typed
    went-away error (and resumable streams migrate)."""
    import cloudpickle

    from ray_tpu.serve._private.replica import Replica

    r = Replica(cloudpickle.dumps((SlowCallable, (), {})))

    class FakePump:
        def __init__(self, age_s):
            self.last_pump = time.time() - age_s
            self.cancels = 0

        def cancel(self):
            self.cancels += 1

    orphan, live = FakePump(60.0), FakePump(0.0)
    r._streams = {"1": orphan, "2": live}
    # Not draining: every pump counts.
    assert r.drain_status()["streams"] == 2
    r.drain()
    st = r.drain_status()
    assert st["streams"] == 1
    # Nothing was cancelled or removed — no silent truncation.
    assert orphan.cancels == 0 and live.cancels == 0
    assert set(r._streams) == {"1", "2"}


def test_resource_stalled_rollout_force_retires_undrained(ray_start_cluster):
    """The stall-breaker survives the drain change, with its trigger
    narrowed to GENUINE placement stalls: on a 1-CPU cluster the v2
    replica cannot place while v1 holds the CPU (tracked actor PENDING),
    so after the 3s stall window ONE old replica is force-retired WITHOUT
    drain and the rollout completes. (A placed-but-slow-starting replica
    no longer trips this branch — that robbed drains; pinned by the
    rolling-update drain oracle in test_serve_ft.py.)"""
    import cloudpickle

    cluster = ray_start_cluster
    cluster.add_node(num_cpus=1, object_store_memory=64 * 1024 * 1024)
    cluster.connect()
    cluster.wait_for_nodes()
    controller, name = _make_controller(drain_timeout_s=60.0)
    try:
        old = controller._replicas[name][0]
        info2 = DeploymentInfo(
            name=name,
            app_name="t",
            import_spec=cloudpickle.dumps((SlowCallable, (), {})),
            config=DeploymentConfig(
                num_replicas=1, version="v2", drain_timeout_s=60.0,
                health_check_period_s=0.5, health_check_timeout_s=5.0,
            ),
        )
        controller.deploy([info2])
        saw_drain = False
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            saw_drain = saw_drain or old.replica_id in controller._draining
            reps = controller._replicas.get(name, [])
            if reps and all(r.version == "v2" for r in reps):
                break
            time.sleep(0.05)
        reps = controller._replicas.get(name, [])
        assert reps and all(r.version == "v2" for r in reps), (
            f"resource-stalled rollout never completed: {reps}"
        )
        # The old replica was retired through the FORCED (undrained) path.
        assert not saw_drain, "stall-breaker routed through drain"
    finally:
        controller.graceful_shutdown()


def test_serve_shutdown_bounds_wedged_controller(drain_cluster):
    """Satellite: serve.shutdown() used to hang FOREVER on an unbounded
    get against a wedged controller; now it is bounded, force-kills the
    controller, and raises the typed error NAMING it."""
    from ray_tpu import serve
    from ray_tpu.exceptions import ActorUnavailableError
    from ray_tpu.serve._private.common import CONTROLLER_NAME

    class Wedged:
        def shutdown_proxies(self):
            return True

        def graceful_shutdown(self):
            time.sleep(600)  # the wedge

    ray_tpu.remote(name=CONTROLLER_NAME)(Wedged).remote()
    # Wait for the fake controller to be resolvable by name.
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        try:
            ray_tpu.get_actor(CONTROLLER_NAME)
            break
        except Exception:
            time.sleep(0.1)
    t0 = time.monotonic()
    with pytest.raises(ActorUnavailableError, match=CONTROLLER_NAME):
        serve.shutdown(timeout_s=3.0)
    assert time.monotonic() - t0 < 30.0, "shutdown was not bounded"
    # The wedged controller was force-killed.
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            ray_tpu.get_actor(CONTROLLER_NAME)
            time.sleep(0.2)
        except Exception:
            return
    pytest.fail("wedged controller was not force-killed")
