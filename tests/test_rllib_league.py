"""Tests for the AlphaStar league trainer (league self-play genre).

Mirrors the reference's alpha_star tests in spirit: the machinery check is
that a league slot trained against an exploitable scripted opponent learns
to beat it (PFSP routes matches there), that exploiters train against the
live main, and that winning mains get frozen into a growing league.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib.env.two_player import (
    RPS_PAYOFF,
    TwoPlayerMatrixEnv,
    scripted_biased_policy,
)


@pytest.fixture(scope="module")
def ray_cluster():
    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


def test_two_player_env_zero_sum():
    env = TwoPlayerMatrixEnv({"rounds": 5})
    oa, ob = env.reset()
    assert oa.shape == (6,) and not oa.any()
    total_a = total_b = 0.0
    for _ in range(5):
        oa, ob, ra, rb, done = env.step(0, 2)  # rock beats scissors
        assert ra == 1.0 and rb == -1.0
        total_a += ra
        total_b += rb
    assert done and total_a == -total_b == 5.0
    # Observations are mirrored: each side sees [mine, theirs].
    assert oa[0] == 1.0 and oa[3 + 2] == 1.0
    assert ob[2] == 1.0 and ob[3 + 0] == 1.0


def test_alpha_star_league_learns_and_grows(ray_cluster):
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib import AlphaStarConfig

    # A rock-heavy scripted player seeds the league: PFSP must route the
    # main agent's matches to it (hard at first), and the main must learn
    # the counter (paper) to a dominant win-rate.
    rocky = scripted_biased_policy(3, favorite=0, p=0.8, seed=1)
    cfg = (
        AlphaStarConfig()
        .environment(TwoPlayerMatrixEnv, env_config={"rounds": 24})
        .training(
            lr=5e-3, entropy_coeff=0.003, episodes_per_slot=6,
            self_play_fraction=0.2, snapshot_interval=8,
            snapshot_min_winrate=0.55, model_hiddens=(32,),
            scripted_league_seeds=[("rocky", rocky)],
        )
        .debugging(seed=0)
    )
    algo = cfg.build()
    algo.setup(cfg.to_dict())
    try:
        for _ in range(30):
            r = algo.step()
        # 1) The main agent exploits the biased seed decisively.
        wr = algo.winrate_vs("rocky", "main", episodes=20)
        assert wr >= 0.8, f"main failed to exploit the biased opponent (wr={wr})"
        # 2) Winning mains were frozen into the league.
        assert r["league_size"] > 1, "no snapshots were added to the league"
        # 3) All three slot kinds trained (finite losses, win-rates logged).
        for slot in ("main", "main_exploiter_0", "league_exploiter_0"):
            assert np.isfinite(r[f"{slot}/loss"])
            assert 0.0 <= r[f"{slot}/winrate"] <= 1.0
        ckpt = algo.save_checkpoint()
        algo.load_checkpoint(ckpt)
        # Reloaded main still beats the seed.
        assert algo.winrate_vs("rocky", "main", episodes=10) >= 0.7
    finally:
        algo.cleanup()
