"""ASGI seam tests: adapter unit tests + serve.ingress end-to-end.

Models the reference's ASGI-boundary coverage (its proxy is an ASGI app
served by uvicorn and serve.ingress mounts user ASGI apps —
python/ray/serve/tests/test_fastapi.py). Here the apps are raw ASGI-3
callables and the server is the aiohttp adapter.
"""

import asyncio
import json
import threading
import urllib.error
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve


async def echo_app(scope, receive, send):
    """ASGI-3 echo: reports method/path/root_path/query/body, status 201."""
    if scope["type"] != "http":
        return
    body = b""
    while True:
        msg = await receive()
        if msg["type"] == "http.request":
            body += msg.get("body", b"")
            if not msg.get("more_body", False):
                break
        else:
            break
    payload = json.dumps(
        {
            "method": scope["method"],
            "path": scope["path"],
            "root_path": scope.get("root_path", ""),
            "query": scope["query_string"].decode(),
            "body": body.decode(),
        }
    ).encode()
    await send(
        {
            "type": "http.response.start",
            "status": 201,
            "headers": [(b"content-type", b"application/json"), (b"x-custom", b"yes")],
        }
    )
    await send({"type": "http.response.body", "body": payload, "more_body": False})


async def chunked_app(scope, receive, send):
    """Streams three chunks with more_body=True."""
    if scope["type"] != "http":
        return
    await receive()
    await send(
        {
            "type": "http.response.start",
            "status": 200,
            "headers": [(b"content-type", b"text/plain")],
        }
    )
    for part in (b"alpha-", b"beta-", b"gamma"):
        await send({"type": "http.response.body", "body": part, "more_body": True})
    await send({"type": "http.response.body", "body": b"", "more_body": False})


def _get(url, data=None, method=None):
    req = urllib.request.Request(url, data=data, method=method)
    try:
        resp = urllib.request.urlopen(req, timeout=30)
        return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


# ---------------------------------------------------------------------------
# Adapter unit tests: AiohttpASGIServer serving raw ASGI apps, no cluster.
# ---------------------------------------------------------------------------


@pytest.fixture()
def asgi_server():
    from ray_tpu.serve._private.asgi import AiohttpASGIServer

    started = threading.Event()
    holder = {}

    async def dispatch(scope, receive, send):
        if scope.get("path", "").startswith("/chunked"):
            await chunked_app(scope, receive, send)
        else:
            await echo_app(scope, receive, send)

    def serve_thread():
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        server = AiohttpASGIServer(dispatch, "127.0.0.1", 0)
        loop.run_until_complete(server.start())
        holder["port"] = server.port
        holder["loop"] = loop
        started.set()
        loop.run_forever()

    t = threading.Thread(target=serve_thread, daemon=True)
    t.start()
    assert started.wait(10)
    yield holder["port"]
    holder["loop"].call_soon_threadsafe(holder["loop"].stop)


def test_adapter_buffered_response(asgi_server):
    status, headers, body = _get(
        f"http://127.0.0.1:{asgi_server}/a/b?x=1&y=", data=b"ping", method="POST"
    )
    assert status == 201
    assert headers.get("x-custom") == "yes"
    out = json.loads(body)
    assert out["method"] == "POST"
    assert out["path"] == "/a/b"
    assert out["query"] == "x=1&y="
    assert out["body"] == "ping"


def test_adapter_streamed_response(asgi_server):
    status, _, body = _get(f"http://127.0.0.1:{asgi_server}/chunked")
    assert status == 200
    assert body == b"alpha-beta-gamma"


# ---------------------------------------------------------------------------
# serve.ingress end-to-end through the proxy.
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def serve_instance():
    ray_tpu.init(num_cpus=8, object_store_memory=128 * 1024 * 1024)
    serve.start()
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def test_ingress_asgi_app(serve_instance):
    @serve.deployment
    @serve.ingress(echo_app)
    class EchoSvc:
        pass

    serve.run(EchoSvc.bind(), route_prefix="/svc")
    host, port = serve.http_address()
    status, headers, body = _get(
        f"http://{host}:{port}/svc/sub/route?k=v", data=b"hello", method="POST"
    )
    assert status == 201
    assert headers.get("x-custom") == "yes"
    out = json.loads(body)
    # Mount semantics: app sees the sub-path; mount point is root_path.
    assert out["path"] == "/sub/route"
    assert out["root_path"] == "/svc"
    assert out["body"] == "hello"
    assert out["query"] == "k=v"
    serve.delete("EchoSvc")


def test_ingress_raw_query_string(serve_instance):
    """Duplicate keys and ordering survive to the app's scope (wire-exact)."""

    @serve.deployment
    @serve.ingress(echo_app)
    class QuerySvc:
        pass

    serve.run(QuerySvc.bind(), route_prefix="/q")
    host, port = serve.http_address()
    _, _, body = _get(f"http://{host}:{port}/q?tag=a&tag=b&z=1")
    assert json.loads(body)["query"] == "tag=a&tag=b&z=1"
    serve.delete("QuerySvc")


def test_ingress_streaming_asgi_app(serve_instance):
    @serve.deployment
    @serve.ingress(chunked_app)
    class ChunkSvc:
        pass

    serve.run(ChunkSvc.bind(), route_prefix="/chunks")
    host, port = serve.http_address()
    status, headers, body = _get(f"http://{host}:{port}/chunks")
    assert status == 200
    assert body == b"alpha-beta-gamma"
    serve.delete("ChunkSvc")


def test_http_response_envelope_status(serve_instance):
    """Non-ASGI deployments can also set status/headers via the envelope."""

    @serve.deployment
    def teapot(request):
        return {
            "__serve_http_response__": True,
            "status": 418,
            "headers": {"x-kind": "teapot", "content-type": "text/plain"},
            "body": "short and stout",
        }

    serve.run(teapot.bind(), route_prefix="/teapot")
    host, port = serve.http_address()
    status, headers, body = _get(f"http://{host}:{port}/teapot")
    assert status == 418
    assert headers.get("x-kind") == "teapot"
    assert body == b"short and stout"
    serve.delete("teapot")
