"""TuneBOHB (KDE density-ratio searcher) + ResourceChangingScheduler.

Reference: python/ray/tune/search/bohb/ (TuneBOHB), schedulers/hb_bohb.py,
schedulers/resource_changing_scheduler.py:590.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import tune


@pytest.fixture(scope="module")
def ray_start_regular():
    ray_tpu.init(num_cpus=6, object_store_memory=128 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


def _objective(config):
    # Deterministic bowl with optimum at (2, 3); best value 0.
    score = -((config["x"] - 2.0) ** 2) - ((config["y"] - 3.0) ** 2)
    tune.report({"score": score})


SPACE = {"x": tune.uniform(0.0, 6.0), "y": tune.uniform(0.0, 6.0)}


def _best_with(search_alg, num_samples):
    results = tune.Tuner(
        _objective,
        tune_config=tune.TuneConfig(
            metric="score", mode="max", num_samples=num_samples,
            search_alg=search_alg, max_concurrent_trials=1,
        ),
    ).fit()
    return results.get_best_result("score", "max").metrics["score"]


def test_bohb_beats_random_search(ray_start_regular):
    from ray_tpu.tune.search import TuneBOHB
    from ray_tpu.tune.search.basic_variant import BasicVariantGenerator

    budget = 24
    bohb_best = _best_with(
        TuneBOHB(dict(SPACE), metric="score", mode="max", min_points=6,
                 random_fraction=0.15, seed=0),
        budget,
    )
    random_best = _best_with(BasicVariantGenerator(dict(SPACE), seed=0), budget)
    # Same seeded budget on a deterministic objective: the model must home
    # in on the bowl while random stays scattershot.
    assert bohb_best >= random_best, (bohb_best, random_best)
    assert bohb_best > -1.0, f"BOHB best {bohb_best} is far from the optimum"


def test_bohb_model_prefers_good_region():
    from ray_tpu.tune.search import TuneBOHB

    searcher = TuneBOHB(
        {"x": tune.uniform(0.0, 1.0)}, metric="score", mode="max",
        min_points=6, random_fraction=0.0, seed=1,
    )
    # Seed observations: high scores cluster at x ~ 0.8 (recorded through
    # on_trial_result — the budget-tagged observation path).
    for i in range(20):
        tid = f"seed{i}"
        x = 0.8 + 0.02 * (i % 3) if i % 2 == 0 else 0.15 + 0.02 * (i % 5)
        searcher._live[tid] = [x]
        searcher.on_trial_result(
            tid, {"score": -abs(x - 0.8) * 10, "training_iteration": 1}
        )
        searcher.on_trial_complete(tid)
    picks = [searcher.suggest(f"t{i}")["x"] for i in range(8)]
    # The density-ratio acquisition concentrates suggestions near the mode.
    assert np.mean([0.6 <= p <= 1.0 for p in picks]) >= 0.75, picks


def test_bohb_uses_largest_budget_with_data():
    from ray_tpu.tune.search import TuneBOHB

    searcher = TuneBOHB({"x": tune.uniform(0.0, 1.0)}, metric="score",
                        mode="max", min_points=3)
    for i in range(6):
        searcher._live[f"a{i}"] = [i / 10]
        searcher.on_trial_result(f"a{i}", {"score": 1.0, "training_iteration": 1})
    for i in range(3):
        searcher._live[f"b{i}"] = [i / 10]
        searcher.on_trial_result(f"b{i}", {"score": 1.0, "training_iteration": 4})
    assert searcher._model_budget() == 4  # highest fidelity with >= min_points
    for i in range(2):
        searcher._live[f"c{i}"] = [i / 10]
        searcher.on_trial_result(f"c{i}", {"score": 1.0, "training_iteration": 9})
    assert searcher._model_budget() == 4  # budget 9 has too few points


def test_hyperband_for_bohb_alias():
    from ray_tpu.tune.schedulers import HyperBandForBOHB, HyperBandScheduler

    assert issubclass(HyperBandForBOHB, HyperBandScheduler) or (
        HyperBandForBOHB is HyperBandScheduler
    )


class _ResourceReporter(tune.Trainable):
    def setup(self, config):
        self.steps_done = 0

    def step(self):
        self.steps_done += 1
        return {
            "score": float(self.iteration),
            "cpus": self.trial_resources.get("CPU", 0),
            "steps_in_this_actor": self.steps_done,
        }

    def save_checkpoint(self):
        from ray_tpu.air.checkpoint import Checkpoint

        return Checkpoint.from_dict({"steps": self.steps_done})

    def load_checkpoint(self, checkpoint):
        self.steps_done = checkpoint.to_dict()["steps"]


def test_resource_changing_scheduler_resizes_running_trial(ray_start_regular):
    from ray_tpu.tune.schedulers import ResourceChangingScheduler

    def grow_after_two(controller, trial, result, scheduler):
        if result.get("training_iteration", 0) >= 2:
            return {"CPU": 2}
        return None

    scheduler = ResourceChangingScheduler(resources_allocation_function=grow_after_two)
    results = tune.Tuner(
        _ResourceReporter,
        tune_config=tune.TuneConfig(
            metric="score", mode="max", num_samples=1,
            scheduler=scheduler, max_concurrent_trials=1,
        ),
        run_config=tune.RunConfig(stop={"training_iteration": 6}),
    ).fit()
    r = results.get_best_result("score", "max")
    # The trial started on the 1-CPU default and finished on 2 CPUs after
    # the mid-run pause/restart.
    assert r.metrics["cpus"] == 2, r.metrics
    assert r.metrics["training_iteration"] >= 6
    # The checkpoint carried progress across the resize: the replacement
    # actor continued from the saved step count instead of redoing work.
    assert r.metrics["steps_in_this_actor"] >= 6
    assert scheduler.reallocated  # exactly the resize we requested
