"""Model zoo smoke + sharded-train-step tests on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ray_tpu.models.mlp import init_mlp, mlp_forward, mlp_loss
from ray_tpu.models.transformer import (
    TransformerConfig,
    forward,
    init_params,
    loss_fn,
    make_train_step,
    num_params,
    param_logical_axes,
)
from ray_tpu.parallel.mesh import MeshConfig, create_mesh, logical_to_spec


def tiny_cfg(**kw):
    defaults = dict(
        vocab_size=128,
        d_model=32,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        d_ff=64,
        max_seq_len=64,
        dtype=jnp.float32,
        remat=False,
    )
    defaults.update(kw)
    return TransformerConfig(**defaults)


def test_mlp_forward_and_loss():
    params = init_mlp(jax.random.PRNGKey(0), (16, 8, 4))
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 16))
    y = jnp.zeros((8,), jnp.int32)
    loss, acc = mlp_loss(params, {"x": x, "y": y})
    assert np.isfinite(float(loss))


def test_transformer_forward_shapes():
    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
    logits, aux = forward(params, tokens, cfg)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert np.isfinite(np.asarray(logits)).all()


def test_transformer_gqa_and_moe():
    cfg = tiny_cfg(num_experts=4)
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
    logits, aux = forward(params, tokens, cfg)
    assert logits.shape == (2, 16, cfg.vocab_size)


def test_transformer_loss_decreases():
    cfg = tiny_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    opt = optax.adam(1e-2)
    opt_state = opt.init(params)
    step = jax.jit(make_train_step(cfg, opt))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 17), 0, cfg.vocab_size)
    batch = {"tokens": tokens}
    losses = []
    for _ in range(10):
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9, losses


def test_transformer_sharded_train_step():
    """Full train step jitted over a dp×tp mesh with logical-axis shardings —
    the single-host version of what __graft_entry__.dryrun_multichip does."""
    from jax.sharding import NamedSharding

    cfg = tiny_cfg()
    mesh = create_mesh(MeshConfig(dp=2, tp=2, fsdp=2))
    params = init_params(jax.random.PRNGKey(0), cfg)
    axes = param_logical_axes(cfg)

    def spec_for(path, leaf):
        node = axes
        for p in path:
            node = node[p.key]
        return logical_to_spec(node)

    params = jax.tree_util.tree_map_with_path(
        lambda path, leaf: jax.device_put(leaf, NamedSharding(mesh, spec_for(path, leaf))),
        params,
    )
    opt = optax.adam(1e-2)
    opt_state = opt.init(params)
    step = jax.jit(make_train_step(cfg, opt))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 17), 0, cfg.vocab_size)
    batch = {"tokens": jax.device_put(tokens, NamedSharding(mesh, logical_to_spec(("batch", None))))}
    params, opt_state, loss = step(params, opt_state, batch)
    assert np.isfinite(float(loss))


def test_resnet_forward():
    from ray_tpu.models.resnet import ResNet18

    model = ResNet18(num_classes=10, dtype=jnp.float32, axis_name=None)
    x = jnp.ones((2, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (2, 10)


def test_vit_forward():
    from ray_tpu.models.vit import ViT_Tiny

    model = ViT_Tiny(num_classes=10, dtype=jnp.float32)
    x = jnp.ones((2, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x)
    out = model.apply(variables, x)
    assert out.shape == (2, 10)


def test_transformer_ring_attention_path():
    """attn_impl='ring' over an sp mesh matches the dense path."""
    cfg = tiny_cfg(n_kv_heads=4)
    mesh = create_mesh(MeshConfig(sp=4, dp=2))
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, cfg.vocab_size)
    dense, _ = forward(params, tokens, cfg)
    ring, _ = forward(params, tokens, cfg, mesh=mesh, attn_impl="ring")
    np.testing.assert_allclose(np.asarray(dense), np.asarray(ring), atol=2e-4)


def test_pallas_flash_attention_matches_xla_fwd_bwd():
    """The pallas kernel (interpret mode on CPU) must match the XLA
    reference in BOTH forward and gradients — the training loss
    differentiates through flash_attention on TPU, so a missing/wrong VJP
    would crash or corrupt every TPU train step."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.ops.attention import _xla_attention, flash_attention

    rng = np.random.default_rng(0)
    B, T, H, D = 2, 256, 2, 32
    q, k, v = (
        jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32) for _ in range(3)
    )
    for causal in (False, True):
        ref = _xla_attention(q, k, v, causal, 0.125)
        out = flash_attention(
            q, k, v, causal=causal, sm_scale=0.125,
            force_pallas=True, interpret=True, block_q=128, block_k=128,
        )
        assert float(jnp.abs(out - ref).max()) < 1e-5

        def loss_p(q, k, v, _c=causal):
            return (flash_attention(q, k, v, causal=_c, sm_scale=0.125,
                                    force_pallas=True, interpret=True) ** 2).sum()

        def loss_x(q, k, v, _c=causal):
            return (_xla_attention(q, k, v, _c, 0.125) ** 2).sum()

        gp = jax.grad(loss_p, argnums=(0, 1, 2))(q, k, v)
        gx = jax.grad(loss_x, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gp, gx):
            rel = float(jnp.abs(a - b).max() / (jnp.abs(b).max() + 1e-9))
            assert rel < 1e-4, f"causal={causal} grad mismatch {rel}"


def test_flash_attention_odd_lengths_fall_back():
    """Non-tileable sequence lengths must route to the XLA path (a clamped
    tail block would double-count rows)."""
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.ops.attention import _xla_attention, flash_attention

    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.standard_normal((1, 100, 2, 16)), jnp.float32)
    out = flash_attention(q, q, q, causal=True, force_pallas=True, interpret=True)
    ref = _xla_attention(q, q, q, True, 0.25)
    assert float(jnp.abs(out - ref).max()) < 1e-5


def test_flash_attention_cross_length_causal_alignment():
    """Tq != Tk causal: both paths must use the same (bottom-right) mask
    alignment — query row i sees keys 0..i+(Tk-Tq), the kv-cache decode
    convention."""
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.ops.attention import _xla_attention, flash_attention

    rng = np.random.default_rng(2)
    q = jnp.asarray(rng.standard_normal((1, 128, 2, 32)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((1, 256, 2, 32)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((1, 256, 2, 32)), jnp.float32)
    ref = _xla_attention(q, k, v, True, 0.125)
    out = flash_attention(q, k, v, causal=True, sm_scale=0.125,
                          force_pallas=True, interpret=True, block_q=64, block_k=64)
    assert float(jnp.abs(out - ref).max()) < 1e-5


def test_pallas_backward_kernels_vs_oracle(monkeypatch):
    """The Pallas dkv/dq backward kernels (transposed-score orientation,
    causal/window loop pruning) must match the XLA attention's autodiff
    exactly — including the Tq != Tk bottom-right alignment and the
    sliding-window mask, at block sizes that exercise multi-block loops."""
    monkeypatch.setenv("RAY_TPU_FLASH_BWD_BLOCK", "128")
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.ops.attention import _xla_attention, flash_attention

    rng = np.random.default_rng(7)
    cases = [
        # (Tq, Tk, causal, window) — 512-length at block 128 gives 4 blocks
        # per axis, so the causal/window loop pruning runs multi-iteration
        # spans (qb_start/qb_end interior values), not just 0..1.
        (512, 512, True, 0),
        (256, 256, False, 0),
        (256, 512, True, 0),    # decode-style cross-length alignment
        (512, 512, True, 192),  # sliding window, multi-block pruning
    ]
    for Tq, Tk, causal, window in cases:
        q = jnp.asarray(rng.standard_normal((2, Tq, 2, 32)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((2, Tk, 2, 32)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((2, Tk, 2, 32)), jnp.float32)

        def loss_p(q, k, v, _c=causal, _w=window):
            return (flash_attention(q, k, v, causal=_c, sm_scale=0.2, window=_w,
                                    force_pallas=True, interpret=True,
                                    block_q=64, block_k=64) ** 2).sum()

        def loss_x(q, k, v, _c=causal, _w=window):
            return (_xla_attention(q, k, v, _c, 0.2, window=_w) ** 2).sum()

        gp = jax.grad(loss_p, argnums=(0, 1, 2))(q, k, v)
        gx = jax.grad(loss_x, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", gp, gx):
            rel = float(jnp.abs(a - b).max() / (jnp.abs(b).max() + 1e-9))
            assert rel < 1e-4, f"T={Tq}/{Tk} causal={causal} w={window} d{name}: {rel}"
