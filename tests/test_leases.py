"""Direct task transport (worker leases) — lease_manager.py + raylet grants.

Mirrors the reference's direct_task_transport tests
(python/ray/tests/test_basic_2.py lease reuse, test_failure_4.py worker
crash retries): tasks ride leased workers, leases are returned when idle,
placement-sensitive tasks keep the classic path, and a killed leased
worker fails over with retries.
"""

import os
import time

import pytest


def test_lease_path_correctness(ray_start_regular):
    import ray_tpu

    @ray_tpu.remote
    def add(x, y):
        return x + y

    # Chains (dependency through owned refs) and fan-out both cross the
    # lease transport.
    assert ray_tpu.get(add.remote(1, 2)) == 3
    assert ray_tpu.get(add.remote(add.remote(1, 2), 10)) == 13
    assert ray_tpu.get([add.remote(i, i) for i in range(50)]) == [2 * i for i in range(50)]


def test_lease_reused_and_returned(ray_start_regular):
    import ray_tpu
    from ray_tpu._private.worker_context import get_core_worker

    @ray_tpu.remote
    def pid():
        return os.getpid()

    # A sync loop should reuse one leased worker (no per-call spawn).
    pids = {ray_tpu.get(pid.remote()) for _ in range(10)}
    assert len(pids) <= 2  # warmup may use a second worker

    cw = get_core_worker()
    lm = cw._lease_mgr
    assert lm is not None
    held = sum(len(s.leases) for s in lm._shapes.values())
    assert held >= 1
    # After the linger the lease is returned to the raylet.
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        held = sum(len(s.leases) for s in lm._shapes.values())
        if held == 0:
            break
        time.sleep(0.2)
    assert held == 0, "idle lease was never returned"
    # ... and the worker goes back to the raylet's idle pool (reusable by
    # the next lease or classic dispatch), not into limbo.
    raylet = getattr(ray_tpu._global_node, "raylet", None)
    if raylet is not None:
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if any(w.state == "idle" for w in raylet.workers.values()):
                break
            time.sleep(0.2)
        assert any(w.state == "idle" for w in raylet.workers.values()), (
            "released lease did not return its worker to the idle pool"
        )


def test_warm_lease_reuse_skips_raylet(monkeypatch):
    """Steady-state sync loop: the raylet grants ONE lease up front; the
    following tasks ship worker-direct — request_worker_lease is not called
    again and every task runs in the same worker process. (The in-process
    raylet shares the test's IO loop, so its handler call counts are
    directly observable.)"""
    import ray_tpu
    from ray_tpu._private.rpc import EventLoopThread

    # Long linger so the maintenance loop cannot return the lease between
    # sync calls on a slow/loaded box.
    monkeypatch.setenv("RAY_TPU_LEASE_IDLE_RELEASE_S", "30")
    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    try:

        @ray_tpu.remote
        def pid():
            return os.getpid()

        first = ray_tpu.get(pid.remote())  # cold: requests the lease
        stats = EventLoopThread.get().handler_stats
        key = next((k for k in stats if k.endswith(".request_worker_lease")), None)
        assert key is not None, "no lease request ever reached the raylet"
        grants_before = stats[key][0]
        pids = [ray_tpu.get(pid.remote()) for _ in range(3)]
        assert pids == [first] * 3, "warm tasks left the leased worker"
        assert stats[key][0] == grants_before, (
            "warm-lease tasks contacted the raylet for new leases"
        )
    finally:
        ray_tpu.shutdown()


def test_sigkill_warm_leased_worker_fails_over(ray_start_regular):
    """SIGKILL of the warm-leased worker: the next task fails over to a
    fresh lease (new worker) without a lost task."""
    import signal

    import ray_tpu

    @ray_tpu.remote(max_retries=2)
    def pid():
        return os.getpid()

    victim = ray_tpu.get(pid.remote())  # lease now warm on this worker
    os.kill(victim, signal.SIGKILL)
    survivor = ray_tpu.get(pid.remote(), timeout=90)
    assert survivor != victim


def test_classic_path_for_placement_sensitive_tasks(ray_start_regular):
    import ray_tpu
    from ray_tpu._private.task_spec import TaskSpec
    from ray_tpu._private.worker_context import get_core_worker

    cw = get_core_worker()
    spread = TaskSpec(task_id="x", job_id="j", name="t", scheduling_strategy="SPREAD")
    pg = TaskSpec(task_id="x", job_id="j", name="t", placement_group_id="abc")
    streaming = TaskSpec(task_id="x", job_id="j", name="t", num_returns="streaming")
    normal = TaskSpec(task_id="x", job_id="j", name="t")
    assert not cw._lease_eligible(spread)
    assert not cw._lease_eligible(pg)
    assert not cw._lease_eligible(streaming)
    assert cw._lease_eligible(normal)

    @ray_tpu.remote(scheduling_strategy="SPREAD")
    def f():
        return "spread-ok"

    assert ray_tpu.get(f.remote()) == "spread-ok"


def test_leased_worker_death_fails_over(ray_start_regular):
    import ray_tpu

    @ray_tpu.remote(max_retries=3)
    def die_once(marker_dir):
        marker = os.path.join(marker_dir, "died")
        if not os.path.exists(marker):
            open(marker, "w").close()
            os._exit(1)  # hard kill mid-lease
        return "recovered"

    import tempfile

    with tempfile.TemporaryDirectory() as d:
        assert ray_tpu.get(die_once.remote(d), timeout=60) == "recovered"


def test_leased_worker_death_without_retries_errors(ray_start_regular):
    import ray_tpu
    from ray_tpu.exceptions import WorkerCrashedError

    @ray_tpu.remote(max_retries=0)
    def die():
        os._exit(1)

    with pytest.raises(WorkerCrashedError):
        ray_tpu.get(die.remote(), timeout=60)


def test_lease_demand_reaches_autoscaler_load(ray_start_regular):
    """Owner-side backlog must surface in the raylet's demand report
    (reference: backlog_size on lease requests)."""
    import ray_tpu

    @ray_tpu.remote
    def slow():
        time.sleep(0.5)
        return 1

    refs = [slow.remote() for _ in range(200)]
    # The in-process raylet: reach it via the global node handle.
    node = ray_tpu._global_node
    raylet = getattr(node, "raylet", None)
    if raylet is None:
        pytest.skip("in-process raylet not reachable")
    # 45s window, peak-tracking: under full-suite load on one core the
    # 200-task backlog can drain through the observation polls — track the
    # MAX seen, and a lower bar still proves backlog reaches the report
    # (flaked in-suite at 15s/50, passes standalone).
    deadline = time.monotonic() + 45
    seen = 0
    while time.monotonic() < deadline:
        load = raylet._pending_load()
        seen = max(seen, sum(e["count"] for e in load))
        if seen >= 50:
            break
        time.sleep(0.1)
    assert seen >= 20, f"demand report never saw the backlog (saw {seen})"
    ray_tpu.get(refs, timeout=300)
