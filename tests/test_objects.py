"""Object store unit + integration tests (arena, serialization, spill)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private import serialization
from ray_tpu._private.store.arena import PyArena, create_arena


class TestArena:
    def test_native_alloc_free(self):
        arena = create_arena("/rtpu_test_arena", 1 << 20)
        try:
            a = arena.alloc(1000)
            b = arena.alloc(2000)
            assert a is not None and b is not None and a != b
            used = arena.used()
            assert used >= 3000
            arena.free(a)
            assert arena.used() < used
            # freed space is reusable
            c = arena.alloc(900)
            assert c is not None
        finally:
            arena.close(unlink=True)

    def test_arena_exhaustion(self):
        arena = create_arena("/rtpu_test_arena2", 1 << 16)
        try:
            assert arena.alloc(1 << 17) is None
        finally:
            arena.close(unlink=True)

    def test_coalescing(self):
        arena = create_arena("/rtpu_test_arena3", 1 << 20)
        try:
            offs = [arena.alloc(1 << 10) for _ in range(8)]
            for off in offs:
                arena.free(off)
            # After freeing everything, one full-size alloc must fit.
            big = arena.alloc((1 << 20) - 128)
            assert big is not None
        finally:
            arena.close(unlink=True)

    def test_py_fallback_parity(self):
        arena = PyArena("rtpu_test_py", 1 << 20, create=True)
        try:
            a = arena.alloc(100)
            arena.write(a, b"x" * 100)
            assert bytes(arena.read(a, 100)) == b"x" * 100
            arena.free(a)
        finally:
            arena.close(unlink=True)


class TestSerialization:
    def test_roundtrip_basic(self):
        for obj in [1, "s", [1, 2], {"k": (1, 2)}, None, b"bytes", {1.5, 2.5}]:
            assert serialization.loads(serialization.dumps(obj)) == obj

    def test_numpy_zero_copy(self):
        arr = np.arange(1000, dtype=np.float64)
        data = serialization.dumps(arr)
        out = serialization.loads(data)
        np.testing.assert_array_equal(out, arr)
        # The deserialized array must be backed by the input buffer (no copy).
        assert not out.flags["OWNDATA"]

    def test_jax_array_to_host(self):
        import jax.numpy as jnp

        x = jnp.arange(16).reshape(4, 4)
        out = serialization.loads(serialization.dumps(x))
        np.testing.assert_array_equal(np.asarray(out), np.arange(16).reshape(4, 4))

    def test_exception_roundtrip(self):
        from ray_tpu.exceptions import TaskError

        try:
            raise ValueError("inner")
        except ValueError as e:
            err = TaskError.from_exception(e, task_name="t")
        out = serialization.loads(serialization.dumps(err))
        assert isinstance(out, TaskError)
        assert "inner" in out.remote_traceback


def test_spilling(ray_start_cluster):
    """Objects exceeding arena capacity spill to disk and restore on get
    (reference: local_object_manager.h:110 SpillObjects)."""
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2, object_store_memory=16 * 1024 * 1024)
    cluster.connect()
    arrays = [np.full((1024, 1024), i, dtype=np.float32) for i in range(8)]  # 8 x 4MB
    refs = [ray_tpu.put(a) for a in arrays]
    for i, ref in enumerate(refs):
        out = ray_tpu.get(ref, timeout=60)
        assert out[0, 0] == i


def test_owner_serves_borrower(ray_start_regular):
    """A small (inline) object is served by its owner to borrowing workers."""
    ref = ray_tpu.put("inline-value")

    @ray_tpu.remote
    def fetch(r):
        return ray_tpu.get(r, timeout=30)

    assert ray_tpu.get(fetch.remote([ref]), timeout=60) == ["inline-value"]


def test_task_returning_refs_keeps_them_alive(ray_start_regular):
    """Refs nested in a returned value survive the producing worker's local
    refs dying (nested-ref borrow handoff; reference: reference_count.h)."""
    import gc
    import time

    @ray_tpu.remote
    def make_refs():
        return {"a": ray_tpu.put("alpha"), "b": [ray_tpu.put(np.arange(50_000))]}

    out = ray_tpu.get(make_refs.remote(), timeout=60)
    gc.collect()
    time.sleep(1.0)  # give any erroneous free a chance to land
    assert ray_tpu.get(out["a"], timeout=30) == "alpha"
    assert ray_tpu.get(out["b"][0], timeout=30).shape == (50_000,)


def test_spilling_through_custom_external_storage(ray_start_cluster, tmp_path, monkeypatch):
    """The external-storage seam (reference: external_storage.py:246): a
    registered custom backend receives every spill/restore/delete instead
    of the default filesystem writer."""
    import json

    from ray_tpu._private.store import external_storage as es

    calls = {"put": 0, "get": 0}

    class CountingStorage(es.FileSystemStorage):
        def put(self, object_id, data):
            calls["put"] += 1
            return super().put(object_id, data)

        def get(self, handle):
            calls["get"] += 1
            return super().get(handle)

    es.register_external_storage(
        "counting", lambda directory_path=None: CountingStorage(str(tmp_path / "spill"))
    )
    monkeypatch.setenv(
        "RAY_TPU_OBJECT_SPILLING_CONFIG", json.dumps({"type": "counting"})
    )
    try:
        cluster = ray_start_cluster
        cluster.add_node(num_cpus=2, object_store_memory=16 * 1024 * 1024)
        cluster.connect()
        arrays = [np.full((1024, 1024), i, dtype=np.float32) for i in range(8)]  # 8 x 4MB
        refs = [ray_tpu.put(a) for a in arrays]
        for i, ref in enumerate(refs):
            assert ray_tpu.get(ref, timeout=60)[0, 0] == i
        assert calls["put"] > 0, "custom storage never received a spill"
        assert calls["get"] > 0, "custom storage never served a restore"
        assert any((tmp_path / "spill").iterdir())
    finally:
        es._factories.pop("counting", None)


def test_smart_open_storage_gated():
    from ray_tpu._private.store.external_storage import SmartOpenStorage

    with pytest.raises(ImportError, match="smart_open"):
        SmartOpenStorage("s3://bucket/prefix")
