"""Workflow durable-execution tests (analog of python/ray/workflow/tests/)."""

import pytest

import ray_tpu
from ray_tpu import workflow
from ray_tpu.dag import InputNode


@pytest.fixture
def workflow_storage(tmp_path):
    workflow.init(str(tmp_path))
    yield str(tmp_path)
    workflow.init(None)


def test_workflow_run(ray_start_regular, workflow_storage):
    @ray_tpu.remote
    def double(x):
        return x * 2

    @ray_tpu.remote
    def add(x, y):
        return x + y

    dag = add.bind(double.bind(3), double.bind(4))
    assert workflow.run(dag, workflow_id="w1") == 14
    assert workflow.get_status("w1") == "SUCCESSFUL"
    assert workflow.get_output("w1") == 14
    assert ("w1", "SUCCESSFUL") in workflow.list_all()


def test_workflow_with_input(ray_start_regular, workflow_storage):
    @ray_tpu.remote
    def inc(x):
        return x + 1

    with InputNode() as inp:
        dag = inc.bind(inc.bind(inp))

    assert workflow.run(dag, 5, workflow_id="w2") == 7


def test_workflow_resume_skips_completed_steps(ray_start_regular, workflow_storage, tmp_path):
    marker = tmp_path / "ran_flaky"

    @ray_tpu.remote
    def stable():
        return 10

    @ray_tpu.remote
    def flaky(x, marker_path):
        import os

        # count executions through a side file, and fail on first attempt
        runs = 1
        if os.path.exists(marker_path):
            with open(marker_path) as f:
                runs = int(f.read()) + 1
        with open(marker_path, "w") as f:
            f.write(str(runs))
        if runs == 1:
            raise RuntimeError("transient failure")
        return x + 1

    dag = flaky.bind(stable.bind(), str(marker))
    with pytest.raises(Exception):
        workflow.run(dag, workflow_id="w3")
    assert workflow.get_status("w3") == "FAILED"

    # resume: stable's result is replayed from the log, flaky re-runs once
    assert workflow.resume("w3") == 11
    assert workflow.get_status("w3") == "SUCCESSFUL"
    with open(marker) as f:
        assert f.read() == "2"


def test_workflow_idempotent_rerun(ray_start_regular, workflow_storage):
    @ray_tpu.remote
    def f():
        return 42

    assert workflow.run(f.bind(), workflow_id="w4") == 42
    # finished workflows return the stored output without re-executing
    assert workflow.run(f.bind(), workflow_id="w4") == 42


def test_workflow_delete(ray_start_regular, workflow_storage):
    @ray_tpu.remote
    def f():
        return 1

    workflow.run(f.bind(), workflow_id="w5")
    workflow.delete("w5")
    assert workflow.get_status("w5") == "NOT_FOUND"
    with pytest.raises(ValueError):
        workflow.get_output("w5")


def test_workflow_rejects_actor_nodes(ray_start_regular, workflow_storage):
    @ray_tpu.remote
    class A:
        def m(self):
            return 1

    with pytest.raises(TypeError):
        workflow.run(A.bind(), workflow_id="w6")
