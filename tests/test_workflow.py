"""Workflow durable-execution tests (analog of python/ray/workflow/tests/)."""

import pytest

import ray_tpu
from ray_tpu import workflow
from ray_tpu.dag import InputNode


@pytest.fixture
def workflow_storage(tmp_path):
    workflow.init(str(tmp_path))
    yield str(tmp_path)
    workflow.init(None)


def test_workflow_run(ray_start_regular, workflow_storage):
    @ray_tpu.remote
    def double(x):
        return x * 2

    @ray_tpu.remote
    def add(x, y):
        return x + y

    dag = add.bind(double.bind(3), double.bind(4))
    assert workflow.run(dag, workflow_id="w1") == 14
    assert workflow.get_status("w1") == "SUCCESSFUL"
    assert workflow.get_output("w1") == 14
    assert ("w1", "SUCCESSFUL") in workflow.list_all()


def test_workflow_with_input(ray_start_regular, workflow_storage):
    @ray_tpu.remote
    def inc(x):
        return x + 1

    with InputNode() as inp:
        dag = inc.bind(inc.bind(inp))

    assert workflow.run(dag, 5, workflow_id="w2") == 7


def test_workflow_resume_skips_completed_steps(ray_start_regular, workflow_storage, tmp_path):
    marker = tmp_path / "ran_flaky"

    @ray_tpu.remote
    def stable():
        return 10

    @ray_tpu.remote
    def flaky(x, marker_path):
        import os

        # count executions through a side file, and fail on first attempt
        runs = 1
        if os.path.exists(marker_path):
            with open(marker_path) as f:
                runs = int(f.read()) + 1
        with open(marker_path, "w") as f:
            f.write(str(runs))
        if runs == 1:
            raise RuntimeError("transient failure")
        return x + 1

    dag = flaky.bind(stable.bind(), str(marker))
    with pytest.raises(Exception):
        workflow.run(dag, workflow_id="w3")
    assert workflow.get_status("w3") == "FAILED"

    # resume: stable's result is replayed from the log, flaky re-runs once
    assert workflow.resume("w3") == 11
    assert workflow.get_status("w3") == "SUCCESSFUL"
    with open(marker) as f:
        assert f.read() == "2"


def test_workflow_idempotent_rerun(ray_start_regular, workflow_storage):
    @ray_tpu.remote
    def f():
        return 42

    assert workflow.run(f.bind(), workflow_id="w4") == 42
    # finished workflows return the stored output without re-executing
    assert workflow.run(f.bind(), workflow_id="w4") == 42


def test_workflow_delete(ray_start_regular, workflow_storage):
    @ray_tpu.remote
    def f():
        return 1

    workflow.run(f.bind(), workflow_id="w5")
    workflow.delete("w5")
    assert workflow.get_status("w5") == "NOT_FOUND"
    with pytest.raises(ValueError):
        workflow.get_output("w5")


def test_workflow_rejects_actor_nodes(ray_start_regular, workflow_storage):
    @ray_tpu.remote
    class A:
        def m(self):
            return 1

    with pytest.raises(TypeError):
        workflow.run(A.bind(), workflow_id="w6")


def test_workflow_concurrent_branches(ray_start_regular, workflow_storage):
    """Independent branches must run concurrently (reference:
    workflow_executor.py executes ready steps in parallel)."""
    import time as _time

    @ray_tpu.remote
    def slow(tag):
        _time.sleep(1.0)
        return tag

    @ray_tpu.remote
    def join(*parts):
        return sorted(parts)

    dag = join.bind(slow.bind("a"), slow.bind("b"), slow.bind("c"))
    t0 = _time.monotonic()
    assert workflow.run(dag, workflow_id="wc1") == ["a", "b", "c"]
    wall = _time.monotonic() - t0
    # 3 x 1s serially would be >=3s; concurrent branches finish in ~1s
    # (4 CPUs in the fixture). Generous bound for slow CI.
    assert wall < 2.8, f"branches ran serially ({wall:.1f}s)"


def test_workflow_step_identity_is_content_derived(ray_start_regular, workflow_storage):
    """An edited DAG (different static arg) must NOT replay the old step's
    checkpoint (VERDICT r2: positional identity replayed stale results)."""

    @ray_tpu.remote
    def produce(x):
        return x * 10

    @ray_tpu.remote
    def finish(v):
        return v

    workflow.run(finish.bind(produce.bind(1)), workflow_id="wid1")
    assert workflow.get_output("wid1") == 10

    # Same workflow id, edited DAG: the changed arg changes the step id, so
    # produce re-runs instead of replaying 10. (Finished workflows replay
    # their OUTPUT by id; use a fresh id to re-execute the edited DAG.)
    assert workflow.run(finish.bind(produce.bind(2)), workflow_id="wid2") == 20


def test_workflow_max_retries(ray_start_regular, workflow_storage, tmp_path):
    """A step that fails transiently succeeds within max_retries."""
    counter = tmp_path / "attempts"

    @ray_tpu.remote
    def flaky():
        n = int(counter.read_text()) if counter.exists() else 0
        counter.write_text(str(n + 1))
        if n < 2:
            raise RuntimeError(f"transient {n}")
        return "ok"

    out = workflow.run(flaky.bind(), workflow_id="wr1", max_retries=3)
    assert out == "ok"
    assert int(counter.read_text()) == 3


def test_workflow_catch_exceptions(ray_start_regular, workflow_storage):
    """catch_exceptions=True boxes step outcomes as (result, error)."""

    @ray_tpu.remote
    def boom():
        raise ValueError("expected failure")

    @ray_tpu.remote
    def ok():
        return 5

    @ray_tpu.remote
    def combine(a, b):
        return {"ok": a, "err": b}

    dag = combine.bind(ok.bind(), boom.bind())
    out = workflow.run(dag, workflow_id="wcx1", catch_exceptions=True)
    # combine itself is caught too: unbox the outer tuple first.
    result, err = out
    assert err is None
    assert result["ok"] == (5, None)
    val, exc = result["err"]
    assert val is None and isinstance(exc, ValueError)


def test_workflow_mid_branch_failure_resume(ray_start_regular, workflow_storage, tmp_path):
    """A failing branch must not lose the OTHER branch's finished steps:
    resume re-runs only the failed branch (reference: failure-resume)."""
    good_runs = tmp_path / "good_runs"
    allow = tmp_path / "allow_bad"

    @ray_tpu.remote
    def good():
        n = int(good_runs.read_text()) if good_runs.exists() else 0
        good_runs.write_text(str(n + 1))
        return "good"

    @ray_tpu.remote
    def bad():
        if not allow.exists():
            raise RuntimeError("branch failure")
        return "bad-recovered"

    @ray_tpu.remote
    def join(a, b):
        return (a, b)

    from ray_tpu.exceptions import TaskError

    dag = join.bind(good.bind(), bad.bind())
    with pytest.raises(TaskError):
        workflow.run(dag, workflow_id="wmb1")
    assert workflow.get_status("wmb1") == "FAILED"
    assert int(good_runs.read_text()) == 1  # good branch completed + persisted

    allow.write_text("1")
    assert workflow.resume("wmb1") == ("good", "bad-recovered")
    # good() was NOT re-executed on resume — its checkpoint replayed.
    assert int(good_runs.read_text()) == 1
    assert workflow.get_status("wmb1") == "SUCCESSFUL"
