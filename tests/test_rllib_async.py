"""Async env-runner + connector pipelines.

Reference: rllib/evaluation/sampler.py:309 (AsyncSampler),
env_runner_v2.py:199 (EnvRunnerV2), rllib/connectors/{agent,action}.
The async runner keeps stepping envs in a background thread while the
learner updates; fragments queue up with backpressure and episode stats
ride along with them.
"""

import time

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def ray_cluster():
    ray_tpu.init(num_cpus=6, object_store_memory=256 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


def _local_worker(env="CartPole-v1", **kw):
    import gymnasium as gym
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib.core import rl_module
    from ray_tpu.rllib.evaluation.rollout_worker import RolloutWorker
    from ray_tpu.rllib.models import ModelCatalog

    probe = gym.make(env)
    spec = ModelCatalog.get_model_spec(
        probe.observation_space, probe.action_space,
        {"fcnet_hiddens": (32,), "conv_filters": None},
    )
    probe.close()
    worker = RolloutWorker(env, spec, worker_index=0, num_envs=1, seed=1, **kw)
    worker.set_weights(rl_module.init_params(__import__("jax").random.PRNGKey(0), spec))
    return worker


def test_async_runner_produces_in_background():
    # The producer thread must fill the fragment queue with NO sampling
    # calls from the consumer — that is the property that lets the learner
    # overlap its update with environment stepping.
    w = _local_worker()
    try:
        w.start_async(fragment_len=32, queue_size=4)
        deadline = time.monotonic() + 30
        while w.async_queue_depth() < 2 and time.monotonic() < deadline:
            time.sleep(0.1)
        assert w.async_queue_depth() >= 2, "producer thread made no fragments"
        items = w.get_async(max_items=8, timeout=5)
        assert len(items) >= 2
        for item in items:
            assert len(item["batch"]) >= 32
            assert "episode_rewards" in item
        # Production continues after a drain.
        items2 = w.get_async(max_items=8, timeout=20)
        assert len(items2) >= 1
    finally:
        w.stop_async()
        w.stop()


def test_async_collects_while_consumer_is_busy():
    # Sync sampling by construction collects ZERO steps while the learner
    # is busy; the async runner keeps going. Simulate a slow update with a
    # sleep and check fragments accumulated during it.
    w = _local_worker()
    try:
        w.start_async(fragment_len=16, queue_size=8)
        # Drain whatever the warmup produced.
        w.get_async(max_items=100, timeout=20)
        time.sleep(2.0)  # "learner update" — no sampling calls
        items = w.get_async(max_items=100, timeout=5)
        steps = sum(len(it["batch"]) for it in items)
        assert steps >= 32, f"only {steps} steps collected during the update gap"
    finally:
        w.stop_async()
        w.stop()


def test_box_envs_get_action_clipping_connector():
    # Continuous envs auto-install a ClipActions stage (the gaussian sample
    # is unbounded); discrete envs install none.
    wc = _local_worker("Pendulum-v1")
    try:
        assert len(wc.action_connectors.connectors) == 1
        batch = wc.sample(8)
        assert len(batch) >= 8  # env accepted the (clipped) actions
    finally:
        wc.stop()
    wd = _local_worker("CartPole-v1")
    try:
        assert len(wd.action_connectors.connectors) == 0
    finally:
        wd.stop()


def test_agent_connector_pipeline_shapes_observations():
    from ray_tpu.rllib.connectors import ClipObservations
    from ray_tpu.rllib.policy.sample_batch import OBS

    w = _local_worker(agent_connectors=[ClipObservations(-0.05, 0.05)])
    try:
        batch = w.sample(16)
        assert np.all(batch[OBS] <= 0.05) and np.all(batch[OBS] >= -0.05)
    finally:
        w.stop()


def test_impala_async_learns_cartpole(ray_cluster):
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib import IMPALAConfig

    cfg = (
        IMPALAConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=2, num_envs_per_worker=4, rollout_fragment_length=128)
        .training(lr=1e-3, train_batch_size=2048, entropy_coeff=0.01, async_sampling=True)
        .debugging(seed=0)
    )
    algo = cfg.build()
    best = 0.0
    try:
        for _ in range(80):
            r = algo.step()
            m = r.get("episode_reward_mean")
            if m is not None and np.isfinite(m):
                best = max(best, m)
            if best >= 100:
                break
        assert best >= 100, f"async IMPALA failed to learn CartPole (best={best})"
    finally:
        algo.cleanup()
