"""Device object plane (experimental/device_object/, ISSUE 9).

Device-resident jax.Array objects passed by reference: ``put(arr,
tensor_transport="collective")`` / ``@remote(tensor_transport=...)`` seal
only a descriptor into the store, the payload stays on the holder's
devices and moves out of band — same-process live array (zero shm copies,
asserted via store counters + flight-recorder events), collective p2p
between group members (sharding preserved bit-exact), transparent
host-shm fallback otherwise. Chaos: SIGKILLed holders surface
DeviceObjectLostError NAMING the holder, a spilled copy rescues the same
get, and out-of-scope refs verifiably free the device buffers.

One module-scoped cluster: creating one per test would dominate tier-1
wall time (see tier-1 budget notes in CHANGES).
"""

import gc
import os
import signal
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.exceptions import DeviceObjectLostError


@pytest.fixture(scope="module")
def dev_cluster():
    ray_tpu.init(num_cpus=6, object_store_memory=256 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


def _store_objects() -> int:
    from ray_tpu._private import worker_context

    cw = worker_context.get_core_worker()
    return cw.raylet.call("get_state")["store"]["num_objects"]


def _driver_events(etype: str) -> list:
    from ray_tpu._private import flight_recorder

    proc = flight_recorder.dump() or {"events": []}
    return [e for e in proc["events"] if e.get("type") == etype]


def _sharded(n=64):
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "tp"))
    x = jnp.arange(float(n), dtype=jnp.float32).reshape(8, n // 8)
    return jax.device_put(x, NamedSharding(mesh, P("dp", "tp")))


@ray_tpu.remote(tensor_transport="collective")
class Holder:
    def pid(self):
        return os.getpid()

    def make(self, n=256):
        import jax.numpy as jnp

        return jnp.arange(float(n), dtype=jnp.float32)

    def make_big(self, n):
        import jax.numpy as jnp

        return jnp.ones((n,), jnp.float32)

    def make_sharded(self):
        return _sharded()

    def init_collective(self, world_size, rank, backend, group_name):
        from ray_tpu.util import collective as col

        col.init_collective_group(world_size, rank, backend=backend, group_name=group_name)

    def spill_all(self):
        from ray_tpu.experimental.device_object.manager import active_manager

        m = active_manager()
        return [m.spill(o) for o in m.object_ids()] if m is not None else []

    def stats(self):
        from ray_tpu.experimental.device_object import device_object_stats

        return device_object_stats()


@ray_tpu.remote
class Consumer:
    def init_collective(self, world_size, rank, backend, group_name):
        from ray_tpu.util import collective as col

        col.init_collective_group(world_size, rank, backend=backend, group_name=group_name)

    def consume(self, w):
        import jax

        assert isinstance(w, jax.Array), type(w)
        return {
            "sum": float(np.asarray(w).sum()),
            "sharding": repr(w.sharding),
            "shards": sorted(
                (s.device.id, tuple(sl.start or 0 for sl in s.index))
                for s in w.addressable_shards
            ),
        }


# ----------------------------------------------------------------------
# acceptance: same-process handoff = zero host-shm copies of the payload
# ----------------------------------------------------------------------


def test_same_process_put_get_is_zero_copy(dev_cluster):
    x = _sharded()
    before_objects = _store_objects()
    before_create = len(_driver_events("devobj_create"))
    before_xfer = len(_driver_events("devobj_transfer"))
    ref = ray_tpu.put(x, tensor_transport="collective")
    out = ray_tpu.get(ref)
    assert out is x  # the LIVE array, not a reassembled copy
    # Store counters: the payload never touched the node's shm arena.
    assert _store_objects() == before_objects
    # Flight recorder: the plane narrated itself.
    creates = _driver_events("devobj_create")
    xfers = _driver_events("devobj_transfer")
    assert len(creates) == before_create + 1
    assert len(xfers) == before_xfer + 1
    assert xfers[-1]["detail"].endswith(":local")
    del ref, out


def test_put_requires_jax_array(dev_cluster):
    with pytest.raises(TypeError, match="jax.Array"):
        ray_tpu.put(np.zeros(4), tensor_transport="collective")
    with pytest.raises(ValueError, match="tensor_transport"):
        ray_tpu.put(_sharded(), tensor_transport="nvlink")

    @ray_tpu.remote(tensor_transport="bogus")
    class Bad:
        pass

    with pytest.raises(ValueError, match="tensor_transport"):
        Bad.remote()

    @ray_tpu.remote
    def fn():
        return 1

    with pytest.raises(ValueError, match="invalid"):
        fn.options(tensor_transport="collective")  # tasks hold no state to be a holder


# ----------------------------------------------------------------------
# acceptance: cross-actor collective path, sharding preserved bit-exact
# ----------------------------------------------------------------------


def test_actor_to_actor_collective_handoff(dev_cluster):
    from ray_tpu.util import collective as col

    holder, consumer = Holder.remote(), Consumer.remote()
    col.create_collective_group([holder, consumer], backend="cpu", group_name="plane")
    before_objects = _store_objects()
    wref = holder.make_sharded.remote()
    out = ray_tpu.get(consumer.consume.remote(wref), timeout=120)
    # Bit-exact, sharding preserved (same mesh axes, same per-device shards).
    assert out["sum"] == float(np.arange(64.0).sum())
    assert "dp" in out["sharding"] and "tp" in out["sharding"]
    assert out["shards"] == sorted(
        (s.device.id, tuple(sl.start or 0 for sl in s.index))
        for s in _sharded().addressable_shards
    )
    st = ray_tpu.get(holder.stats.remote())
    assert st["transfers_collective"] >= 1, st
    # The payload rode the collective plane, not the shm store.
    assert _store_objects() == before_objects
    del wref
    ray_tpu.kill(holder)
    ray_tpu.kill(consumer)


# ----------------------------------------------------------------------
# no-group / cross-mesh fallback (transparent host path)
# ----------------------------------------------------------------------


def test_no_group_fallback_small_inline(dev_cluster):
    holder = Holder.remote()
    ref = holder.make.remote(256)
    out = ray_tpu.get(ref, timeout=60)  # driver shares no group with holder
    np.testing.assert_array_equal(np.asarray(out), np.arange(256.0))
    del ref
    ray_tpu.kill(holder)


def test_no_group_fallback_large_via_store(dev_cluster):
    holder = Holder.remote()
    n = 1 << 20  # 4 MiB — far past the inline cutoff
    ref = holder.make_big.remote(n)
    out = ray_tpu.get(ref, timeout=120)
    assert float(np.asarray(out).sum()) == float(n)
    # Second get resolves again (from the sealed arena copy or the holder).
    out2 = ray_tpu.get(ref, timeout=120)
    assert float(np.asarray(out2).sum()) == float(n)
    del ref, out, out2
    ray_tpu.kill(holder)


def test_device_ref_as_normal_task_arg(dev_cluster):
    """A device ref passed to a plain (non-actor) task resolves through the
    existing arg-resolution path in the leased worker."""
    holder = Holder.remote()
    ref = holder.make.remote(64)

    @ray_tpu.remote
    def total(w):
        return float(np.asarray(w).sum())

    assert ray_tpu.get(total.remote(ref), timeout=120) == float(np.arange(64.0).sum())
    ready, _ = ray_tpu.wait([ref], timeout=10)
    assert ready == [ref]
    del ref
    ray_tpu.kill(holder)


# ----------------------------------------------------------------------
# spill / restore under memory pressure
# ----------------------------------------------------------------------


def test_driver_spill_limit_and_restore(dev_cluster):
    from ray_tpu._private.config import get_config
    from ray_tpu.experimental.device_object import device_object_stats

    import jax.numpy as jnp

    cfg = get_config()
    cfg.devobj_resident_limit_bytes = 6000
    try:
        before = device_object_stats()
        r1 = ray_tpu.put(jnp.ones(1000, jnp.float32), tensor_transport="collective")
        r2 = ray_tpu.put(jnp.full(1000, 2.0, jnp.float32), tensor_transport="collective")
        st = device_object_stats()
        # 8000 resident bytes > 6000 limit: the LRU entry (r1) spilled.
        assert st["spills"] == before["spills"] + 1, st
        assert st["resident_bytes"] <= 6000, st
        v1 = ray_tpu.get(r1)  # restore on next resolve
        np.testing.assert_array_equal(np.asarray(v1), np.ones(1000))
        assert device_object_stats()["restores"] == before["restores"] + 1
        np.testing.assert_array_equal(np.asarray(ray_tpu.get(r2)), np.full(1000, 2.0))
        del r1, r2, v1
    finally:
        cfg.devobj_resident_limit_bytes = 0


# ----------------------------------------------------------------------
# chaos: holder death
# ----------------------------------------------------------------------


def test_sigkill_holder_names_it_in_lost_error(dev_cluster):
    holder = Holder.remote()
    pid = ray_tpu.get(holder.pid.remote())
    ref = holder.make.remote(512)
    ready, _ = ray_tpu.wait([ref], timeout=60)  # descriptor sealed at owner
    assert ready
    os.kill(pid, signal.SIGKILL)
    time.sleep(0.5)
    with pytest.raises(DeviceObjectLostError) as err:
        ray_tpu.get(ref, timeout=60)
    assert holder.actor_id[:16] in str(err.value)
    del ref


def test_sigkill_holder_with_spilled_copy_survives(dev_cluster):
    holder = Holder.remote()
    pid = ray_tpu.get(holder.pid.remote())
    ref = holder.make.remote(2048)
    ready, _ = ray_tpu.wait([ref], timeout=60)
    assert ready
    assert ray_tpu.get(holder.spill_all.remote()) == [True]
    os.kill(pid, signal.SIGKILL)
    time.sleep(0.5)
    out = ray_tpu.get(ref, timeout=60)  # host copy in the arena rescues it
    np.testing.assert_array_equal(np.asarray(out), np.arange(2048.0))
    del ref, out


# ----------------------------------------------------------------------
# ownership: device buffers freed when refs go out of scope (no leak)
# ----------------------------------------------------------------------


def test_no_leak_across_100_iterations(dev_cluster):
    holder = Holder.remote()
    base = ray_tpu.get(holder.stats.remote())
    for i in range(100):
        ref = holder.make.remote(128)
        out = ray_tpu.get(ref, timeout=60)
        assert float(np.asarray(out)[1]) == 1.0
        del ref, out
    gc.collect()
    deadline = time.time() + 30
    while time.time() < deadline:
        st = ray_tpu.get(holder.stats.remote())
        if (
            st["resident_count"] == base["resident_count"]
            and st["frees"] >= base["frees"] + 100
        ):
            break
        time.sleep(0.2)
    assert st["resident_count"] == base["resident_count"], st
    assert st["creates"] >= base["creates"] + 100
    assert st["frees"] >= base["frees"] + 100
    ray_tpu.kill(holder)


# ----------------------------------------------------------------------
# state view
# ----------------------------------------------------------------------


def test_state_view_lists_device_objects(dev_cluster):
    from ray_tpu.util.state import list_device_objects

    x = _sharded()
    ref = ray_tpu.put(x, tensor_transport="collective")
    oid = ref.hex()
    deadline = time.time() + 10
    rows = []
    while time.time() < deadline:
        rows = [r for r in list_device_objects() if r["object_id"] == oid]
        if rows:
            break
        time.sleep(0.1)
    assert rows, "device object never appeared in the state view"
    row = rows[0]
    assert row["nbytes"] == x.nbytes and row["holder_kind"] == "driver"
    del ref
    gc.collect()
    deadline = time.time() + 10
    while time.time() < deadline:
        if not [r for r in list_device_objects() if r["object_id"] == oid]:
            return
        time.sleep(0.1)
    raise AssertionError("freed device object still listed in the state view")
