"""Sharded checkpointing: save on one mesh layout, restore on another.

The multi-host essential: flagship params sharded tp=4/dp=2 survive a
round trip onto a RESHAPED mesh (tp=2/dp=4) with correct values AND the
new shardings — job resumes after resizes, inference loads training
checkpoints under its own layout.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ray_tpu.train.jax.checkpointing import (
    TrainCheckpointer,
    restore_sharded,
    save_sharded,
)


def _mesh(tp, dp):
    from ray_tpu.parallel.mesh import MeshConfig, create_mesh

    return create_mesh(MeshConfig(tp=tp, dp=dp))


def _sharded_params(cfg, mesh):
    from jax.sharding import NamedSharding

    from ray_tpu.models.transformer import init_params, param_logical_axes
    from ray_tpu.parallel.mesh import logical_to_spec, shard_pytree

    params = init_params(jax.random.PRNGKey(0), cfg)
    axes = param_logical_axes(cfg)

    def spec_for(path, _leaf):
        node = axes
        for p in path:
            node = node[p.key]
        return logical_to_spec(node)

    return shard_pytree(params, mesh, spec_for), spec_for


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8-device virtual mesh")
def test_reshard_on_restore(tmp_path):
    from jax.sharding import NamedSharding

    from ray_tpu.models.transformer import TransformerConfig

    cfg = TransformerConfig(
        vocab_size=128, d_model=64, n_layers=2, n_heads=4, n_kv_heads=4,
        d_ff=128, dtype=jnp.float32, remat=False,
    )
    mesh_a = _mesh(tp=4, dp=2)
    params, spec_for = _sharded_params(cfg, mesh_a)
    path = save_sharded(str(tmp_path / "ck"), params)

    # Restore onto a RESHAPED mesh.
    mesh_b = _mesh(tp=2, dp=4)
    like = jax.tree_util.tree_map_with_path(
        lambda p, leaf: jax.ShapeDtypeStruct(
            leaf.shape, leaf.dtype, sharding=NamedSharding(mesh_b, spec_for(p, leaf))
        ),
        params,
    )
    restored = restore_sharded(path, like=like)
    for (kp, a), (_, b) in zip(
        jax.tree_util.tree_flatten_with_path(params)[0],
        jax.tree_util.tree_flatten_with_path(restored)[0],
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=str(kp))
    wq = restored["layers"]["wq"]
    assert wq.sharding.mesh.shape["tp"] == 2, wq.sharding


def test_train_checkpointer_retention(tmp_path):
    ck = TrainCheckpointer(str(tmp_path / "run"), keep=2)
    tree = {"w": jnp.arange(8.0), "step": jnp.int32(0)}
    for step in (1, 5, 9, 12):
        ck.save(step, {**tree, "step": jnp.int32(step)})
    assert ck.latest_step() == 12
    assert ck._steps() == [9, 12]  # keep=2 reaped 1 and 5
    got = ck.restore()
    assert int(got["step"]) == 12
    got5 = ck.restore(step=9)
    assert int(got5["step"]) == 9
    with pytest.raises(FileNotFoundError):
        TrainCheckpointer(str(tmp_path / "empty")).restore()


def test_rollback_save_is_not_self_deleting(tmp_path):
    """Retention and latest rank by SAVE RECENCY: after a rollback, saving
    a lower step must not delete itself, and resume must pick the rollback
    lineage, not a stale higher-numbered future step."""
    ck = TrainCheckpointer(str(tmp_path / "run"), keep=2)
    for step in (9, 12):
        ck.save(step, {"step": jnp.int32(step)})
    ck.save(10, {"step": jnp.int32(10)})  # rollback to 9, continue from 10
    assert 10 in ck._steps()  # did not delete itself
    assert ck.latest_step() == 10  # resume point is the newest SAVE
    assert int(ck.restore()["step"]) == 10
    ck.save(11, {"step": jnp.int32(11)})
    assert sorted(ck._steps()) == [10, 11]  # stale step_12 finally reaped


def test_interrupted_swap_recovers_on_read(tmp_path):
    """A crash between save_sharded's two renames leaves only path+'.old';
    the next read finishes the swap instead of losing the checkpoint."""
    import os

    path = str(tmp_path / "ck")
    save_sharded(path, {"w": jnp.arange(4.0)})
    os.rename(path, path + ".old")  # simulate dying mid-swap
    got = restore_sharded(path)
    np.testing.assert_array_equal(np.asarray(got["w"]), np.arange(4.0))
    assert os.path.exists(path) and not os.path.exists(path + ".old")


def test_overwrite_is_durable_swap(tmp_path):
    """Re-saving the same path keeps data consistent and leaves no tmp
    residue (the old checkpoint is only replaced after the new one is
    fully finalized)."""
    import os

    path = str(tmp_path / "ck")
    save_sharded(path, {"w": jnp.zeros(4)})
    save_sharded(path, {"w": jnp.ones(4)})
    got = restore_sharded(path)
    np.testing.assert_array_equal(np.asarray(got["w"]), np.ones(4))
    siblings = sorted(os.listdir(tmp_path))
    assert siblings == ["ck"], siblings  # no .saving/.old residue
