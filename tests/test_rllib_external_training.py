"""Serving-abstraction integration: external simulators TRAIN live algorithms.

The reference's cartpole_server/client pattern (rllib/env/policy_server_input
as config.input_; examples/serving/): an external process owns the env loop,
gets actions over HTTP from the algorithm's policy, and the completed
episodes feed the algorithm's training. Two paths covered:

- MARWIL via ExternalInputReader (PolicyServerInput as config.input_ — the
  reference's exact wiring for offline-capable algorithms), and
- DQN via replay-buffer ingestion (external SampleBatches share the buffer
  schema with on-policy rollouts).
"""

import threading

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib.env import PolicyClient, PolicyServerInput


@pytest.fixture(scope="module")
def ray_cluster():
    import jax

    jax.config.update("jax_platforms", "cpu")
    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


def _drive_external_episodes(address, n_episodes, policy=None, max_steps=40):
    """External-sim loop: gymnasium CartPole stepped CLIENT-side, actions
    from the server (or a local scripted policy logged via log_action)."""
    import gymnasium as gym

    client = PolicyClient(address)
    returns = []
    env = gym.make("CartPole-v1")
    for _ in range(n_episodes):
        obs, _ = env.reset(seed=int(np.random.default_rng().integers(1 << 30)))
        eid = client.start_episode()
        total, steps = 0.0, 0
        while True:
            if policy is None:
                action = client.get_action(eid, obs.astype(np.float32))
            else:
                action = policy(obs)
                client.log_action(eid, obs.astype(np.float32), action)
            obs, r, term, trunc, _ = env.step(int(action))
            client.log_returns(eid, float(r))
            total += float(r)
            steps += 1
            if term or trunc or steps >= max_steps:
                client.end_episode(eid, obs.astype(np.float32))
                break
        returns.append(total)
    return returns


def test_marwil_trains_from_external_clients(ray_cluster):
    """PolicyServerInput as config.input_: client-side expert episodes flow
    through ExternalInputReader into MARWIL updates (the reference's
    input-reader wiring for external experiences)."""
    from ray_tpu.rllib import MARWILConfig

    server = PolicyServerInput(compute_action=lambda obs, explore: 0)
    try:
        expert = lambda obs: int(obs[2] > 0)  # push toward the pole's lean
        _drive_external_episodes(server.address, n_episodes=6, policy=expert)

        cfg = (
            MARWILConfig()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=0)
            .training(lr=5e-3, train_batch_size=128, beta=1.0)
            .debugging(seed=0)
        )
        cfg.offline_data(input_=server)
        algo = cfg.build()
        algo.setup(cfg.to_dict())
        try:
            m = algo.step()
            assert np.isfinite(m.get("loss", m.get("total_loss", np.nan))), m
            # More external episodes mid-training fold into the window.
            _drive_external_episodes(server.address, n_episodes=2, policy=expert)
            m2 = algo.step()
            assert np.isfinite(m2.get("loss", m2.get("total_loss", np.nan))), m2
            assert algo._timesteps_total > 0
        finally:
            algo.cleanup()
    finally:
        server.shutdown()


def test_input_reader_kwargs_reach_the_reader(ray_cluster):
    """config.offline_data(input_reader_kwargs=...) tunes the external
    reader (slow-simulator timeout etc.) without bypassing the input_ seam."""
    from ray_tpu.rllib import MARWILConfig

    server = PolicyServerInput(compute_action=lambda obs, explore: 0)
    try:
        _drive_external_episodes(server.address, 1, policy=lambda o: 0, max_steps=5)
        cfg = MARWILConfig().environment("CartPole-v1").rollouts(num_rollout_workers=0)
        cfg.offline_data(
            input_=server,
            input_reader_kwargs={"timeout_s": 5.0, "min_episodes": 1, "window_rows": 256},
        )
        algo = cfg.build()
        algo.setup(cfg.to_dict())
        try:
            assert algo.reader._timeout == 5.0
            assert algo.reader._window.capacity == 256
        finally:
            algo.cleanup()
    finally:
        server.shutdown()


def test_dqn_serves_actions_and_trains_on_external_episodes(ray_cluster):
    """The live algorithm's policy answers client get_action; its replay
    buffer ingests the collected external episodes and a gradient step
    runs on them."""
    from ray_tpu.rllib import DQNConfig

    cfg = (
        DQNConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=0)
        .training(learning_starts=0, train_batch_size=32)
        .debugging(seed=0)
    )
    algo = cfg.build()
    algo.setup(cfg.to_dict())
    server = PolicyServerInput(
        compute_action=lambda obs, explore: int(
            algo.compute_single_action(np.asarray(obs, np.float32))
        )
    )
    try:
        returns = _drive_external_episodes(server.address, n_episodes=4)
        assert len(returns) == 4 and all(r > 0 for r in returns)
        batch = server.next_batch(min_episodes=4)
        assert batch is not None and len(batch) == int(sum(returns))
        algo.buffer.add(batch)
        algo._timesteps_total += len(batch)
        metrics = algo._train_once()
        loss = next(v for k, v in metrics.items() if "loss" in k.lower())
        assert np.isfinite(loss), metrics
    finally:
        server.shutdown()
        algo.cleanup()


def test_concurrent_external_clients(ray_cluster):
    """Multiple client sims against one server: episode isolation holds.
    Every client stamps its thread id into all its observations AND
    actions, so cross-episode contamination (one client's rows landing in
    another's episode) is directly detectable — not just contiguity."""
    server = PolicyServerInput(compute_action=lambda obs, explore: 1)
    steps_per_ep, eps_per_client, n_clients = 7, 3, 3

    def drive(tid):
        client = PolicyClient(server.address)
        for ep in range(eps_per_client):
            eid = client.start_episode()
            for step in range(steps_per_ep):
                obs = np.array([tid, ep, step, 0], np.float32)
                client.log_action(eid, obs, int(tid))
                client.log_returns(eid, float(tid))
            client.end_episode(eid, np.array([tid, ep, steps_per_ep, 0], np.float32))

    try:
        threads = [threading.Thread(target=drive, args=(t,)) for t in range(n_clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        batch = server.next_batch(min_episodes=n_clients * eps_per_client)
        assert batch is not None
        assert len(batch) == n_clients * eps_per_client * steps_per_ep
        eps = np.asarray(batch["eps_id"])
        obs = np.asarray(batch["obs"])
        acts = np.asarray(batch["actions"])
        rews = np.asarray(batch["rewards"])
        dones = np.asarray(batch["dones"])
        assert len(set(eps.tolist())) == n_clients * eps_per_client
        # Episodes must be CONTIGUOUS runs each ending in done=1 —
        # _add_return_targets's single backward scan (resetting on dones)
        # depends on this batch layout.
        changes = np.flatnonzero(np.diff(eps) != 0)
        assert len(set(eps.tolist())) == len(changes) + 1
        for boundary in changes:
            assert dones[boundary] == 1.0
        assert dones[-1] == 1.0
        for e in set(eps.tolist()):
            rows = eps == e
            tids = obs[rows][:, 0]
            # All rows of one episode belong to exactly one client...
            assert len(set(tids.tolist())) == 1, f"episode {e} mixes clients"
            tid = tids[0]
            # ...and carry that client's actions/rewards/step sequence.
            assert (acts[rows] == tid).all()
            assert (rews[rows] == tid).all()
            assert obs[rows][:, 2].tolist() == list(range(steps_per_ep))
    finally:
        server.shutdown()
