"""Partitioned reads, file-metadata providers, webdataset + mongo sources.

Reference: python/ray/data/datasource/partitioning.py:34 (Partitioning),
file_meta_provider.py:20 (FileMetadataProvider), webdataset_datasource.py,
mongo_datasource.py.
"""

import os

import numpy as np
import pandas as pd
import pytest

import ray_tpu
import ray_tpu.data as rd
from ray_tpu.data import Partitioning


@pytest.fixture(scope="module")
def ray_cluster():
    ray_tpu.init(num_cpus=6, object_store_memory=256 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


def _write_hive_tree(base):
    import pyarrow as pa
    import pyarrow.parquet as pq

    rows = 0
    for year in (2023, 2024):
        for country in ("fr", "de"):
            d = os.path.join(base, f"year={year}", f"country={country}")
            os.makedirs(d)
            n = 5 if year == 2023 else 3
            pq.write_table(
                pa.table({"x": list(range(n))}), os.path.join(d, "part-0.parquet")
            )
            rows += n
    return rows


def test_hive_partitioning_adds_columns(ray_cluster, tmp_path):
    base = str(tmp_path / "tree")
    total = _write_hive_tree(base)
    part = Partitioning("hive", base_dir=base, field_types={"year": int})
    ds = rd.read_parquet(base, partitioning=part)
    df = ds.to_pandas()
    assert len(df) == total
    assert set(df.columns) >= {"x", "year", "country"}
    assert set(df["year"].unique()) == {2023, 2024}  # cast by field_types
    assert set(df["country"].unique()) == {"fr", "de"}
    assert len(df[df["year"] == 2023]) == 10


def test_partition_filter_prunes_before_read(ray_cluster, tmp_path):
    base = str(tmp_path / "tree")
    _write_hive_tree(base)
    part = Partitioning("hive", base_dir=base)
    ds = rd.read_parquet(
        base, partitioning=part,
        partition_filter=lambda f: f["year"] == "2024" and f["country"] == "fr",
    )
    df = ds.to_pandas()
    assert len(df) == 3
    assert set(df["country"].unique()) == {"fr"}
    # Pruning everything is an explicit error, not an empty dataset.
    with pytest.raises(ValueError):
        rd.read_parquet(base, partitioning=part, partition_filter=lambda f: False)


def test_dir_partitioning(ray_cluster, tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq

    base = str(tmp_path / "dirtree")
    for split in ("train", "test"):
        d = os.path.join(base, split, "v1")
        os.makedirs(d)
        pq.write_table(pa.table({"x": [1, 2]}), os.path.join(d, "f.parquet"))
    part = Partitioning("dir", base_dir=base, field_names=["split", "version"])
    df = rd.read_parquet(base, partitioning=part).to_pandas()
    assert set(df["split"].unique()) == {"train", "test"}
    assert set(df["version"].unique()) == {"v1"}


def test_parquet_metadata_provider_exact_rows(tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq

    from ray_tpu.data import DefaultFileMetadataProvider, ParquetMetadataProvider

    f = str(tmp_path / "f.parquet")
    pq.write_table(pa.table({"x": list(range(42))}), f)
    meta = ParquetMetadataProvider().get_metadata([f])
    assert meta.num_rows == 42  # footer-only, no data pages read
    assert meta.size_bytes > 0
    d = DefaultFileMetadataProvider().get_metadata([f])
    assert d.num_rows == -1 and d.size_bytes == os.path.getsize(f)


def test_webdataset_roundtrip(ray_cluster, tmp_path):
    out = str(tmp_path / "shards")
    items = [
        {"__key__": f"sample{i:04d}", "txt": f"hello {i}", "cls": i % 3,
         "meta": {"idx": i}}
        for i in range(20)
    ]
    ds = rd.from_items(items, parallelism=2)
    files = ds.write_webdataset(out)
    assert files and all(f.endswith(".tar") for f in files)
    back = rd.read_webdataset(out).to_pandas().sort_values("__key__").reset_index(drop=True)
    assert len(back) == 20
    assert back.loc[5, "txt"] == "hello 5"
    assert int(back.loc[5, "cls"]) == 5 % 3
    assert back.loc[5, "meta"]["idx"] == 5


def test_mongo_datasource_partitions_with_injected_client(ray_cluster):
    docs = [{"_id": i, "v": i * i} for i in range(30)]

    class FakeCollection:
        def count_documents(self, q):
            return len(docs)

        def aggregate(self, stages):
            out = list(docs)
            for st in stages:
                if "$sort" in st:
                    for key, direction in reversed(list(st["$sort"].items())):
                        out = sorted(out, key=lambda d: d[key], reverse=direction < 0)
                elif "$skip" in st:
                    out = out[st["$skip"]:]
                elif "$limit" in st:
                    out = out[: st["$limit"]]
                elif "$match" in st:
                    kv = st["$match"]
                    out = [d for d in out if all(d.get(k) == v for k, v in kv.items())]
            return iter(out)

    ds = rd.read_mongo(
        "mongodb://unused", "db", "coll",
        collection_factory=FakeCollection, parallelism=4,
    )
    df = ds.to_pandas()
    assert len(df) == 30
    assert sorted(df["v"]) == [i * i for i in range(30)]
    assert "_id" not in df.columns


def test_mongo_requires_pymongo_without_factory(ray_cluster):
    with pytest.raises(ImportError):
        rd.read_mongo("mongodb://x", "db", "coll")
