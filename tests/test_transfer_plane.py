"""Transfer-plane overhaul (ISSUE 10): striped pulls with ranked failover,
the pull admission byte budget, raw-frame negotiation fallback, chunk
boundary bit-exactness on the real node-to-node path, and cut-through
broadcast relays.

One module-scoped cluster (tier-1 budget: a cluster per test would dominate
wall time); the multi-node broadcast sweep builds its own wider cluster and
is marked `slow`. Node "SIGKILL" is simulated with Cluster.remove_node —
the in-process multi-raylet cluster is the reference's
multi-node-without-a-cluster trick, and remove_node is its node-death lever
(cluster_utils.py).
"""

import asyncio
import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private.config import get_config
from ray_tpu._private.rpc import EventLoopThread
from ray_tpu._private.transfer_stats import TRANSFER

CHUNK = get_config().object_transfer_chunk_bytes


def _oid(tag: str) -> str:
    """Deterministic, valid ObjectID hex (the native store index decodes
    ids from hex, so test ids must be real 28-byte hex strings)."""
    return tag.encode().hex().ljust(56, "0")[:56]


@pytest.fixture(scope="module")
def transfer_cluster():
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster()
    nodes = [
        cluster.add_node(num_cpus=1, object_store_memory=192 * 1024 * 1024)
        for _ in range(4)
    ]
    cluster.connect()
    cluster.wait_for_nodes()
    yield cluster, nodes
    cluster.shutdown()


def _io():
    return EventLoopThread.get()


def _seal_raw(node, oid: str, data: bytes):
    """Plant an exact-size object straight in a node's store (ray_tpu.put
    adds serialization framing; wire-boundary tests need byte-exact sizes)."""
    io = _io()
    offset = io.run(node.store.create(oid, len(data)))
    assert offset is not None
    node.arena.write(offset, data)
    node.store.seal(oid)
    io.run(
        node.gcs.acall(
            "add_object_location", {"object_id": oid, "node_id": node.node_id}
        )
    )


def _read_copy(node, oid: str) -> bytes:
    io = _io()
    offset, size = io.run(node.store.get(oid))
    try:
        return bytes(node.arena.read(offset, size))
    finally:
        node.store.release(oid)


def _broadcast(root, oid: str, targets, timeout=120.0):
    return _io().run(
        root.rpc_broadcast_object(
            {
                "object_id": oid,
                "targets": [
                    {"node_id": n.node_id, "address": list(n.address)} for n in targets
                ],
                "timeout": timeout,
            }
        ),
        timeout=timeout,
    )


def _free(nodes, oid: str):
    for n in nodes:
        try:
            n.store.delete(oid)
        except Exception:
            pass


@pytest.mark.parametrize(
    "size", [1, CHUNK - 1, CHUNK, CHUNK + 1], ids=["1B", "chunk-1", "chunk", "chunk+1"]
)
def test_push_bit_exact_at_chunk_boundaries(transfer_cluster, size):
    """Raw-frame push lands bit-exact for sizes straddling chunk edges."""
    cluster, nodes = transfer_cluster
    head, target = nodes[0], nodes[1]
    rng = np.random.default_rng(size)
    data = rng.integers(0, 255, size, dtype=np.uint8).tobytes()
    oid = _oid(f"boundary{size}")
    raw_before = TRANSFER.chunks_raw_out
    _seal_raw(head, oid, data)
    resp = _broadcast(head, oid, [target])
    assert resp["ok"], resp
    assert _read_copy(target, oid) == data
    # The negotiated default on this cluster IS the raw path.
    assert TRANSFER.chunks_raw_out > raw_before
    _free(nodes, oid)


def test_push_negotiation_falls_back_to_msgpack(transfer_cluster):
    """A receiver that does not advertise raw (mixed-version peer /
    transfer_raw_frames=False) gets the object over msgpack chunks —
    bit-exact, no raw frames on the session."""
    cluster, nodes = transfer_cluster
    head, target = nodes[0], nodes[2]
    data = np.arange(CHUNK + 123, dtype=np.uint8).tobytes()
    oid = _oid("fallback")
    _seal_raw(head, oid, data)
    target.raw_frames_enabled = False
    raw_before = TRANSFER.chunks_raw_out
    mp_before = TRANSFER.chunks_msgpack_out
    try:
        resp = _broadcast(head, oid, [target])
        assert resp["ok"], resp
        assert _read_copy(target, oid) == data
        assert TRANSFER.chunks_msgpack_out > mp_before
        assert TRANSFER.chunks_raw_out == raw_before
    finally:
        target.raw_frames_enabled = True
    _free(nodes, oid)


def test_pull_stripes_across_two_replicas(transfer_cluster):
    """A pull with two known locations fetches chunks from BOTH (striping),
    and the result is bit-exact."""
    cluster, nodes = transfer_cluster
    head, replica, puller = nodes[0], nodes[1], nodes[3]
    data = np.random.default_rng(7).integers(
        0, 255, 16 * 1024 * 1024, dtype=np.uint8
    ).tobytes()
    oid = _oid("striped")
    _seal_raw(head, oid, data)
    resp = _broadcast(head, oid, [replica])
    assert resp["ok"], resp
    sources_before = TRANSFER.pull_sources
    ok = _io().run(puller.pull_manager.pull(oid, 60.0), timeout=90)
    assert ok
    assert _read_copy(puller, oid) == data
    assert TRANSFER.pull_sources - sources_before == 2
    _free(nodes, oid)


def test_pull_completes_when_source_node_dies_mid_pull(transfer_cluster):
    """Chaos (the ISSUE 10 satellite): kill a source node while it is
    serving chunks of an in-flight pull. The pull manager demotes the dead
    source and completes from the surviving replica."""
    cluster, nodes = transfer_cluster
    head, puller = nodes[0], nodes[3]
    victim = cluster.add_node(num_cpus=1, object_store_memory=192 * 1024 * 1024)
    cluster.wait_for_nodes()
    data = np.random.default_rng(13).integers(
        0, 255, 32 * 1024 * 1024, dtype=np.uint8
    ).tobytes()
    oid = _oid("failover")
    _seal_raw(head, oid, data)
    assert _broadcast(head, oid, [victim])["ok"]

    # Slow the victim's chunk serving so the kill is guaranteed mid-pull,
    # and flag the first chunk request so the kill happens only once the
    # victim is actually serving this pull.
    serving = threading.Event()
    orig = victim.server._handlers["fetch_object_chunk"]

    async def slow_fetch(req):
        serving.set()
        await asyncio.sleep(0.4)
        return await orig(req)

    victim.server._handlers["fetch_object_chunk"] = slow_fetch

    demotions_before = TRANSFER.source_demotions
    pull_fut = _io().spawn(puller.pull_manager.pull(oid, 120.0))
    assert serving.wait(timeout=30), "victim never served a chunk"
    cluster.remove_node(victim)  # node death mid-pull
    assert pull_fut.result(timeout=120)
    assert _read_copy(puller, oid) == data
    assert TRANSFER.source_demotions > demotions_before
    _free(nodes, oid)


def test_pull_admission_budget_stalls_and_completes(transfer_cluster):
    """Two concurrent pulls larger than the byte budget: the second queues
    (admission_stall flight event + counter) instead of over-committing the
    arena, then runs when the first releases its reservation."""
    from ray_tpu._private import flight_recorder

    cluster, nodes = transfer_cluster
    head, puller = nodes[0], nodes[3]
    datas, oids = [], []
    for i in range(2):
        data = np.random.default_rng(20 + i).integers(
            0, 255, 12 * 1024 * 1024, dtype=np.uint8
        ).tobytes()
        oid = _oid(f"admit{i}")
        _seal_raw(head, oid, data)
        datas.append(data)
        oids.append(oid)

    stalls_before = TRANSFER.admission_stalls
    budget_before = puller.pull_manager.budget
    puller.pull_manager.budget = 8 * 1024 * 1024  # < one object
    try:
        io = _io()
        futs = [io.spawn(puller.pull_manager.pull(oid, 120.0)) for oid in oids]
        assert all(f.result(timeout=120) for f in futs)
    finally:
        puller.pull_manager.budget = budget_before
    for oid, data in zip(oids, datas):
        assert _read_copy(puller, oid) == data
    assert TRANSFER.admission_stalls > stalls_before
    events = (flight_recorder.dump() or {"events": []})["events"]
    assert any(e["type"] == "admission_stall" for e in events)
    for oid in oids:
        _free(nodes, oid)


def test_cut_through_relay_forwards_before_seal(transfer_cluster):
    """Broadcast through a relay chain records transfer_relay (the child
    began forwarding from its in-flight session, not after sealing) and
    every node ends bit-exact."""
    from ray_tpu._private import flight_recorder

    cluster, nodes = transfer_cluster
    head, targets = nodes[0], nodes[1:4]
    data = np.random.default_rng(42).integers(
        0, 255, 20 * 1024 * 1024, dtype=np.uint8
    ).tobytes()
    oid = _oid("cutthru")
    relays_before = TRANSFER.relays
    _seal_raw(head, oid, data)
    resp = _broadcast(head, oid, targets)
    assert resp["ok"], resp
    for t in targets:
        assert _read_copy(t, oid) == data
    # 3 targets -> binomial split (child+1-subtree, child+0) -> >=1 relay.
    assert TRANSFER.relays > relays_before
    events = (flight_recorder.dump() or {"events": []})["events"]
    assert any(e["type"] == "transfer_relay" for e in events)
    _free(nodes, oid)


@pytest.mark.slow
def test_broadcast_sweep_many_nodes():
    """Wider cut-through sweep: 8 nodes, 32 MiB, every copy bit-exact and
    aggregate throughput recorded. Slow-marked: tier-1 is past its wall
    budget; microbench --transfer covers the perf number."""
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util.object_transfer import broadcast_object

    cluster = Cluster()
    try:
        for _ in range(8):
            cluster.add_node(num_cpus=1, object_store_memory=96 * 1024 * 1024)
        cluster.connect()
        cluster.wait_for_nodes()
        data = np.random.default_rng(0).integers(
            0, 255, 32 * 1024 * 1024, dtype=np.uint8
        )
        ref = ray_tpu.put(data)
        t0 = time.perf_counter()
        pushed = broadcast_object(ref, timeout=600)
        dt = time.perf_counter() - t0
        assert pushed == 7
        out = ray_tpu.get(ref)
        np.testing.assert_array_equal(np.asarray(out), data)
        assert dt < 600
    finally:
        cluster.shutdown()
