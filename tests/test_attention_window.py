"""Sliding-window flash attention vs the XLA reference (interpret mode).

Covers the kernel's k-block pruning lower bound, the fully-masked-block
NaN guard, and the custom-VJP backward under a window.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops.attention import _xla_attention, flash_attention


def _qkv(T=256, B=2, H=2, D=32):
    mk = lambda s: jax.random.normal(jax.random.PRNGKey(s), (B, T, H, D))
    return mk(0), mk(1), mk(2)


@pytest.mark.parametrize("window", [64, 96, 1])  # 96: not block-aligned
def test_windowed_kernel_matches_reference(window):
    q, k, v = _qkv()
    D = q.shape[-1]
    ref = _xla_attention(q, k, v, True, D**-0.5, None, window=window)
    got = flash_attention(
        q, k, v, causal=True, window=window, force_pallas=True,
        interpret=True, block_q=64, block_k=64,
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_windowed_backward_matches_reference():
    q, k, v = _qkv(T=128)
    D = q.shape[-1]
    W = 32

    def f(q, k, v):
        return flash_attention(
            q, k, v, causal=True, window=W, force_pallas=True,
            interpret=True, block_q=32, block_k=32,
        ).sum()

    def fr(q, k, v):
        return _xla_attention(q, k, v, True, D**-0.5, None, window=W).sum()

    g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(fr, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5)


def test_wide_window_equals_full_causal():
    q, k, v = _qkv(T=128)
    full = flash_attention(q, k, v, causal=True, force_pallas=True,
                           interpret=True, block_q=64, block_k=64)
    wide = flash_attention(q, k, v, causal=True, window=10_000, force_pallas=True,
                           interpret=True, block_q=64, block_k=64)
    # Value-level f32 equivalence, not bitwise: the full-causal path takes
    # the split-at-the-diagonal loop (no mask select below the diagonal)
    # while the windowed path keeps the uniform masked loop, so the two
    # compile to different programs with different fusion/rounding.
    np.testing.assert_allclose(np.asarray(wide), np.asarray(full), rtol=1e-5, atol=1e-6)
    with pytest.raises(ValueError, match="causal"):
        flash_attention(q, k, v, causal=False, window=8)
